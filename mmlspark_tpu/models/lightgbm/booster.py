"""Serializable GBDT booster: fitted trees + binner + prediction programs.

Reference analogue: `LightGBMBooster` (lightgbm/LightGBMBooster.scala:12-339) — the
serializable model-string wrapper with score/predictLeaf/featureImportance entry points.
Two deliberate departures, per the TPU-first design:
- prediction is a batched jit program over all rows (the reference scores row-by-row
  through JNI `LGBM_BoosterPredictForMatSingle`, LightGBMBooster.scala:258-275 — a pattern
  SURVEY.md §3.1 flags as the thing to replace);
- the model also exports to the LightGBM text format (`saveNativeModel`,
  LightGBMBooster.scala:277-296) so parity against upstream tooling stays checkable.
"""

from __future__ import annotations

import io
import json
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...compile.aot import AOTStore, load_serving_callable
from ...compile.cache import cached_jit
from ...ops.binning import BinMapper
from ...ops.boosting import Tree, tree_apply_raw
from ...ops.objectives import get_objective


class Booster:
    """Fitted gradient-boosting model.

    trees: Tree namedtuple of numpy arrays stacked [T, ...] (single-output) or
    [T, K, ...] (multiclass). thresholds: real-valued split thresholds of the same
    leading shape as trees.split_bin.
    """

    def __init__(self, trees: Tree, thresholds: np.ndarray, init_score: np.ndarray,
                 objective: str, num_class: int, num_features: int,
                 bin_mapper: Optional[BinMapper] = None,
                 feature_names: Optional[List[str]] = None,
                 best_iteration: Optional[int] = None,
                 learning_rate: float = 0.1,
                 average_output: bool = False):
        self.trees = Tree(*[np.asarray(a) for a in trees])
        self.thresholds = np.asarray(thresholds)
        self.init_score = np.asarray(init_score, dtype=np.float32)
        self.objective = objective
        self.num_class = num_class
        self.num_features = num_features
        self.bin_mapper = bin_mapper
        self.feature_names = feature_names or [f"Column_{i}"
                                               for i in range(num_features)]
        self.best_iteration = best_iteration
        self.learning_rate = learning_rate
        # rf mode: prediction is the average of tree outputs, not the sum
        # (LightGBM model-file `average_output` flag)
        self.average_output = average_output
        # AOT serving artifacts (compile/aot.py): set by
        # load_serving_artifacts; _aot_cache memoizes per-batch-bucket
        # Exported programs (None = counted fallback already taken)
        self._aot_store = None
        self._aot_cache: dict = {}

    def __getstate__(self):
        # Exported executables are process-local (and not picklable);
        # a rehydrated booster re-loads them from its store lazily
        state = dict(self.__dict__)
        state["_aot_cache"] = {}
        return state

    def __setstate__(self, state):
        # boosters pickled before the AOT fields existed must rehydrate
        # with them present (pickle bypasses __init__)
        self.__dict__.update(state)
        self.__dict__.setdefault("_aot_store", None)
        self.__dict__.setdefault("_aot_cache", {})

    # ------------------------------------------------------------ properties
    @property
    def multiclass(self) -> bool:
        return self.trees.split_slot.ndim == 3

    @property
    def num_iterations(self) -> int:
        return self.trees.split_slot.shape[0]

    def _used_iters(self) -> int:
        return (self.best_iteration if self.best_iteration is not None
                else self.num_iterations)

    # ------------------------------------------------------------ prediction
    def _prep_x(self, x: np.ndarray) -> np.ndarray:
        """For boosters trained HERE, clip categorical feature codes into the
        bin range exactly like BinMapper.transform did at training time, so
        out-of-range categories route identically at train and serve time.
        Parsed upstream models (bin_mapper None) keep upstream semantics:
        out-of-bitset categories go right."""
        x = np.asarray(x, np.float32)
        bm = self.bin_mapper
        if bm is not None and getattr(bm, "categorical", ()):
            width = self.trees.split_mask.shape[-1]
            if width > 1:
                x = x.copy()
                for ci in bm.categorical:
                    x[:, ci] = np.clip(x[:, ci], 0, width - 1)
        return x

    @staticmethod
    def _pad_rows_pow2(x: np.ndarray) -> np.ndarray:
        """Pad rows up to the next power of two so the jit prediction
        program compiles once per size bucket instead of once per exact batch
        size — a serving loop with ragged batches would otherwise retrace on
        every request (the dynamic-batching dispatcher in io/serving.py uses
        the same bucketing)."""
        n = x.shape[0]
        target = 1 << max(n - 1, 0).bit_length()
        if target == n:
            return x
        pad = np.zeros((target - n,) + x.shape[1:], x.dtype)
        return np.concatenate([x, pad], axis=0)

    def raw_predict(self, x: np.ndarray) -> np.ndarray:
        """Margin scores: [N] (single-output) or [N, K]. Batched jit
        traversal; when AOT serving artifacts are loaded
        (load_serving_artifacts) the matching per-batch-bucket exported
        executable runs instead, with counted fallback to fresh JIT on any
        mismatch."""
        n = x.shape[0]
        x = jnp.asarray(self._pad_rows_pow2(self._prep_x(x)))
        t_used = self._used_iters()
        trees = Tree(*[jnp.asarray(a[:t_used]) for a in self.trees])
        thr = jnp.asarray(self.thresholds[:t_used])
        init = jnp.asarray(self.init_score)
        raw = None
        if self._aot_store is not None:
            raw = self._aot_raw_predict(trees, thr, init, x)
        if raw is None:
            raw = _raw_predict_jit(trees, thr, init, x, self.multiclass)
        raw = np.asarray(raw)[:n]
        if self.average_output and t_used > 0:
            raw = np.asarray(self.init_score) + (
                raw - np.asarray(self.init_score)) / t_used
        return raw

    # ------------------------------------------------------- AOT artifacts
    def _aot_flat_args(self, trees: Tree, thr, init, x) -> list:
        return list(trees) + [thr, init, x]

    def _aot_raw_predict(self, trees: Tree, thr, init, x):
        """Run the exported program for this batch bucket, or None (counted
        fallback) so the caller JITs. Never raises."""
        name = f"raw_predict_b{x.shape[0]}"
        flat = self._aot_flat_args(trees, thr, init, x)
        if name not in self._aot_cache:
            self._aot_cache[name] = load_serving_callable(
                self._aot_store, name, tuple(flat), expect_nr_devices=1)
        fn = self._aot_cache[name]
        if fn is None:
            return None
        try:
            return fn(*flat)
        except Exception:
            from ...compile.aot import count_fallback
            count_fallback("call_error", name)
            self._aot_cache[name] = None
            return None

    def export_serving_artifacts(self, directory: str,
                                 batch_sizes=(1, 2, 4, 8, 16, 32, 64),
                                 include_compiled: bool = True
                                 ) -> List[str]:
        """AOT-export the raw-predict program for the given serving batch
        buckets (rounded up to the pow2 discipline of _pad_rows_pow2) into
        ``directory`` (artifact files + atomic MANIFEST.json): the portable
        ``jax.export`` layer plus (by default) the pre-compiled executable
        layer for this exact backend. Stored beside the model's
        checkpoint/zoo entry so a serving worker starts from precompiled
        executables. Returns the manifest entry names."""
        from jax import export as jax_export
        store = AOTStore(directory)
        t_used = self._used_iters()
        trees = Tree(*[jnp.asarray(a[:t_used]) for a in self.trees])
        flat = list(trees) + [jnp.asarray(self.thresholds[:t_used]),
                              jnp.asarray(self.init_score)]
        fn = jax.jit(partial(_flat_raw_predict, self.multiclass))
        names = []
        done = set()
        for b in batch_sizes:
            b = 1 << max(int(b) - 1, 0).bit_length()
            if b in done:
                continue
            done.add(b)
            specs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat]
            specs.append(jax.ShapeDtypeStruct((b, self.num_features),
                                              jnp.float32))
            exported = jax_export.export(fn)(*specs)
            from ...compile.aot import compile_for_export
            compiled = (compile_for_export(fn, *specs) if include_compiled
                        else None)
            name = f"raw_predict_b{b}"
            store.save(name, exported, compiled=compiled, extra={
                "entry_point": "gbdt_raw_predict", "batch": b,
                "t_used": int(t_used), "num_class": int(self.num_class),
                "num_features": int(self.num_features),
                "objective": self.objective,
                "multiclass": bool(self.multiclass)})
            names.append(name)
        return names

    def load_serving_artifacts(self, directory: str) -> "Booster":
        """Arm AOT serving: predict calls consult ``directory``'s manifest
        first and fall back (counted) to fresh JIT on any mismatch."""
        self._aot_store = AOTStore(directory)
        self._aot_cache = {}
        return self

    def score(self, x: np.ndarray) -> np.ndarray:
        """Prediction-space output (probability / mean), matching
        LightGBMBooster.score semantics (LightGBMBooster.scala:195-228)."""
        obj = get_objective(self.objective, self.num_class)
        raw = self.raw_predict(x)
        return np.asarray(obj.link(jnp.asarray(raw)))

    def predict_leaf(self, x: np.ndarray) -> np.ndarray:
        """Leaf index per tree: [N, T] or [N, T*K] (predictLeaf,
        LightGBMBooster.scala:216-228)."""
        n = x.shape[0]
        x = jnp.asarray(self._pad_rows_pow2(self._prep_x(x)))
        t_used = self._used_iters()
        trees = Tree(*[jnp.asarray(a[:t_used]) for a in self.trees])
        thr = jnp.asarray(self.thresholds[:t_used])
        leaves = _predict_leaf_jit(trees, thr, x, self.multiclass)
        out = np.asarray(leaves)[..., :n]
        if out.ndim == 3:  # [T,K,N] -> [N, T*K]
            return out.transpose(2, 0, 1).reshape(n, -1)
        return out.T

    def features_shap(self, x: np.ndarray) -> np.ndarray:
        """Per-feature SHAP contributions (featuresShap, LightGBMBooster.scala:218-228,
        C++ `C_API_PREDICT_CONTRIB`). [N, F+1] or [N, K*(F+1)]; last column per
        class block is the expected value."""
        from .shap import tree_shap
        x = np.asarray(self._prep_x(x), np.float64)
        t_used = self._used_iters()
        fp1 = self.num_features + 1
        if self.multiclass:
            out = np.zeros((x.shape[0], self.num_class * fp1))
            for k in range(self.num_class):
                trees_k = [Tree(*[np.asarray(a[t, k]) for a in self.trees])
                           for t in range(t_used)]
                thr_k = [np.asarray(self.thresholds[t, k])
                         for t in range(t_used)]
                phi_k = tree_shap(trees_k, thr_k, x, self.num_features,
                                  float(self.init_score[k]))
                if self.average_output and t_used > 0:
                    base = float(self.init_score[k])
                    phi_k[:, :-1] /= t_used
                    phi_k[:, -1] = base + (phi_k[:, -1] - base) / t_used
                out[:, k * fp1:(k + 1) * fp1] = phi_k
            return out
        trees = [Tree(*[np.asarray(a[t]) for a in self.trees])
                 for t in range(t_used)]
        thrs = [np.asarray(self.thresholds[t]) for t in range(t_used)]
        phi = tree_shap(trees, thrs, x, self.num_features,
                        float(self.init_score))
        if self.average_output and t_used > 0:
            base = float(self.init_score)
            phi[:, :-1] /= t_used
            phi[:, -1] = base + (phi[:, -1] - base) / t_used
        return phi

    # -------------------------------------------------------- introspection
    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        """Reference: LightGBMBooster.featureImportances (LightGBMBooster.scala:303-310),
        `LGBM_BoosterFeatureImportance` split/gain modes."""
        feats = self.trees.split_feat.reshape(-1)
        valid = self.trees.split_valid.reshape(-1)
        gains = self.trees.split_gain.reshape(-1)
        out = np.zeros(self.num_features, np.float64)
        if importance_type == "split":
            np.add.at(out, feats[valid], 1.0)
        elif importance_type == "gain":
            np.add.at(out, feats[valid], gains[valid])
        else:
            raise ValueError("importance_type must be 'split' or 'gain'")
        return out

    # --------------------------------------------------------- serialization
    def to_dict(self) -> dict:
        return {
            "objective": self.objective,
            "num_class": self.num_class,
            "num_features": self.num_features,
            "feature_names": self.feature_names,
            "best_iteration": self.best_iteration,
            "learning_rate": self.learning_rate,
            "init_score": self.init_score.tolist(),
            "average_output": self.average_output,
            "categorical": list(self.bin_mapper.categorical
                                if self.bin_mapper else ()),
        }

    def save_arrays(self) -> dict:
        arrays = {f"tree_{f}": np.asarray(getattr(self.trees, f))
                  for f in Tree._fields}
        arrays["thresholds"] = self.thresholds
        if self.bin_mapper is not None:
            arrays["bin_edges"] = self.bin_mapper.edges
            arrays["bin_missing"] = np.asarray(self.bin_mapper.missing, bool)
            if getattr(self.bin_mapper, "feature_min", None) is not None:
                arrays["feature_min"] = self.bin_mapper.feature_min
                arrays["feature_max"] = self.bin_mapper.feature_max
        return arrays

    @staticmethod
    def from_parts(meta: dict, arrays: dict) -> "Booster":
        if "tree_split_default_left" not in arrays:
            # checkpoints from before decision_type support: our trees always
            # trained with default-left + numeric missing NaN / cat missing None
            valid = np.asarray(arrays["tree_split_valid"])
            is_cat = np.asarray(arrays["tree_split_is_cat"])
            arrays = dict(arrays)
            arrays["tree_split_default_left"] = np.ones_like(valid)
            arrays["tree_split_missing_type"] = np.where(is_cat, 0, 2).astype(
                np.int32)
        trees = Tree(*[arrays[f"tree_{f}"] for f in Tree._fields])
        bm = (BinMapper(arrays["bin_edges"],
                        tuple(meta.get("categorical", ())),
                        arrays.get("feature_min"), arrays.get("feature_max"),
                        arrays.get("bin_missing"))
              if "bin_edges" in arrays else None)
        return Booster(trees, arrays["thresholds"],
                       np.asarray(meta["init_score"], np.float32),
                       meta["objective"], meta["num_class"],
                       meta["num_features"], bm, meta["feature_names"],
                       meta["best_iteration"], meta["learning_rate"],
                       meta.get("average_output", False))

    def _objective_config_str(self) -> str:
        """Upstream objective config string shared by the text model and the
        JSON dump (binary sigmoid:1 / multiclass num_class:K / ...)."""
        return {"binary": "binary sigmoid:1",
                "multiclass": f"multiclass num_class:{self.num_class}",
                "multiclassova":
                f"multiclassova num_class:{self.num_class} sigmoid:1",
                }.get(self.objective, self.objective)

    # ------------------------------------------------- LightGBM text format
    def save_native_model(self, path: str) -> None:
        """Write LightGBM-compatible text model (saveNativeModel,
        LightGBMBooster.scala:277-290)."""
        with open(path, "w") as f:
            f.write(self.model_string())

    def dump_model(self, path: Optional[str] = None) -> str:
        """Upstream-style JSON model dump (dumpModel,
        LightGBMBooster.scala:288-296 / C++ `LGBM_BoosterDumpModel`): header +
        `tree_info` with nested `tree_structure` per tree. Returns the JSON
        string; also writes it when `path` is given."""
        import json
        t_used = self._used_iters()
        num_tree_per_it = self.num_class if self.multiclass else 1
        tree_info = []
        tree_id = 0
        for t in range(t_used):
            for k in range(num_tree_per_it):
                if self.multiclass:
                    tree = Tree(*[np.asarray(a[t, k]) for a in self.trees])
                    thr = np.asarray(self.thresholds[t, k])
                    shift = float(self.init_score[k]) / max(t_used, 1)
                else:
                    tree = Tree(*[np.asarray(a[t]) for a in self.trees])
                    thr = np.asarray(self.thresholds[t])
                    shift = float(self.init_score) / max(t_used, 1)
                struct = _tree_to_json(tree, thr, shift)
                tree_info.append({
                    "tree_index": tree_id,
                    "num_leaves": int(np.asarray(tree.split_valid).sum()) + 1,
                    "shrinkage": 1,
                    "tree_structure": struct,
                })
                tree_id += 1
        obj_str = self._objective_config_str()
        doc = {
            "name": "tree",
            "version": "v3",
            "num_class": self.num_class if self.multiclass else 1,
            "num_tree_per_iteration": num_tree_per_it,
            "label_index": 0,
            "max_feature_idx": self.num_features - 1,
            "objective": obj_str,
            "average_output": bool(self.average_output),
            "feature_names": list(self.feature_names),
            "tree_info": tree_info,
        }
        text = json.dumps(doc, indent=2)
        if path:
            with open(path, "w") as f:
                f.write(text)
        return text

    def model_string(self) -> str:
        t_used = self._used_iters()
        num_tree_per_it = self.num_class if self.multiclass else 1
        obj_str = self._objective_config_str()
        out = io.StringIO()
        out.write("tree\n")
        out.write("version=v3\n")
        out.write(f"num_class={self.num_class if self.multiclass else 1}\n")
        out.write(f"num_tree_per_iteration={num_tree_per_it}\n")
        out.write("label_index=0\n")
        out.write(f"max_feature_idx={self.num_features - 1}\n")
        out.write(f"objective={obj_str}\n")
        out.write("feature_names=" + " ".join(self.feature_names) + "\n")
        bm = self.bin_mapper
        if (bm is not None and getattr(bm, "feature_min", None) is not None
                and bm.feature_max is not None):
            # real value ranges captured at fit (upstream [min:max] form)
            infos = []
            for j in range(self.num_features):
                lo, hi = bm.feature_min[j], bm.feature_max[j]
                infos.append(f"[{lo:g}:{hi:g}]"
                             if np.isfinite(lo) and np.isfinite(hi)
                             else "[-inf:inf]")
        else:
            infos = ["[-inf:inf]"] * self.num_features
        out.write("feature_infos=" + " ".join(infos) + "\n")
        out.write("\n")
        tree_id = 0
        for t in range(t_used):
            for k in range(num_tree_per_it):
                if self.multiclass:
                    tree = Tree(*[np.asarray(a[t, k]) for a in self.trees])
                    thr = self.thresholds[t, k]
                else:
                    tree = Tree(*[np.asarray(a[t]) for a in self.trees])
                    thr = self.thresholds[t]
                shift = (float(self.init_score if not self.multiclass
                               else self.init_score[k])
                         / max(t_used, 1))
                out.write(_tree_to_text(tree, thr, tree_id, shift))
                tree_id += 1
        out.write("end of trees\n\n")
        fi = self.feature_importances("split")
        pairs = sorted([(self.feature_names[i], int(v))
                        for i, v in enumerate(fi) if v > 0],
                       key=lambda p: -p[1])
        out.write("feature importances:\n")
        for name, v in pairs:
            out.write(f"{name}={v}\n")
        out.write("\nparameters:\n[boosting: gbdt]\n"
                  f"[objective: {self.objective}]\n"
                  f"[learning_rate: {self.learning_rate}]\n"
                  "end of parameters\n")
        return out.getvalue()


def concat_boosters(a: "Booster", b: "Booster") -> "Booster":
    """Append b's trees after a's (continued/batch training,
    LightGBMBase.scala:29-50 + LGBM_BoosterMerge in TrainUtils.scala:165-168).
    b must have been trained with a's predictions as init margins; the merged
    init score is a's."""
    if a.multiclass != b.multiclass or a.num_features != b.num_features:
        raise ValueError("cannot merge boosters with different shapes")
    la = a.trees.leaf_value.shape[-1]
    lb = b.trees.leaf_value.shape[-1]
    lcap = max(la, lb)
    wcap = max(a.trees.split_mask.shape[-1], b.trees.split_mask.shape[-1])

    def pad_arr(arr, n_extra):
        widths = [(0, 0)] * (arr.ndim - 1) + [(0, n_extra)]
        return np.pad(np.asarray(arr), widths)

    def pad(tree: Tree, thr, l_from):
        extra = lcap - l_from
        fields = {}
        for name, arr in zip(Tree._fields, tree):
            arr = np.asarray(arr)
            if name == "split_mask":
                # leaf axis is -2 here; also unify category-mask widths
                widths = ([(0, 0)] * (arr.ndim - 2)
                          + [(0, extra), (0, wcap - arr.shape[-1])])
                fields[name] = np.pad(arr, widths)
            else:
                fields[name] = pad_arr(arr, extra)
        return Tree(**fields), pad_arr(thr, extra)

    ta, tha = pad(a.trees, a.thresholds, la)
    tb, thb = pad(b.trees, b.thresholds, lb)
    trees = Tree(*[np.concatenate([np.asarray(x), np.asarray(y)], axis=0)
                   for x, y in zip(ta, tb)])
    thr = np.concatenate([tha, thb], axis=0)
    return Booster(trees, thr, a.init_score, a.objective, a.num_class,
                   a.num_features, b.bin_mapper or a.bin_mapper,
                   a.feature_names, None, b.learning_rate, a.average_output)


def _slots_to_nodes(tree: Tree, thresholds: np.ndarray):
    """Convert slot/replay representation to LightGBM node arrays.

    Slot numbering deliberately matches LightGBM's leaf numbering (new right child
    gets leaf index = current leaf count), so leaves map 1:1.
    Returns (split_feature, threshold, left_child, right_child, leaf_value) with
    LightGBM child conventions: >=0 internal node id, <0 means ~leaf_index.
    """
    valid = np.asarray(tree.split_valid)
    n_splits = int(valid.sum())
    if n_splits == 0:
        return (np.zeros(0, int), np.zeros(0), np.zeros(0, int),
                np.zeros(0, int), np.asarray([tree.leaf_value[0]]),
                np.asarray([tree.leaf_count[0]]))
    split_feature = np.zeros(n_splits, int)
    threshold = np.zeros(n_splits)
    left_child = np.zeros(n_splits, int)
    right_child = np.zeros(n_splits, int)
    # pointer[slot] = (node, side) edge currently leading to that leaf slot.
    # When a slot is split at step s it becomes internal node s: the edge that led
    # to it is rewired to node s, and the two child edges take over the pointers.
    pointer = {0: None}
    for s in range(n_splits):
        slot = int(tree.split_slot[s])
        split_feature[s] = int(tree.split_feat[s])
        threshold[s] = float(thresholds[s])
        p = pointer[slot]
        if p is not None:
            node, side = p
            (left_child if side == 0 else right_child)[node] = s
        pointer[slot] = (s, 0)
        pointer[s + 1] = (s, 1)
    # every surviving pointer entry is a leaf edge
    for slot, p in pointer.items():
        if p is None:
            continue
        node, side = p
        (left_child if side == 0 else right_child)[node] = ~slot
    leaf_value = np.asarray(tree.leaf_value[:n_splits + 1], np.float64)
    leaf_count = np.asarray(tree.leaf_count[:n_splits + 1], np.float64)
    return (split_feature, threshold, left_child, right_child, leaf_value,
            leaf_count)


def _tree_to_text(tree: Tree, thresholds: np.ndarray, tree_id: int,
                  value_shift: float) -> str:
    sf, thr, lc, rc, lv, lcnt = _slots_to_nodes(tree, thresholds)
    n_leaves = len(lv)
    n_splits = len(sf)
    is_cat = np.asarray(tree.split_is_cat[:n_splits]).astype(bool)
    num_cat = int(is_cat.sum())
    out = io.StringIO()
    out.write(f"Tree={tree_id}\n")
    out.write(f"num_leaves={n_leaves}\n")
    out.write(f"num_cat={num_cat}\n")
    if n_splits:
        # categorical splits use LightGBM bitset encoding: threshold = index
        # into cat_boundaries; cat_threshold bit c set => category c goes left.
        # decision_type: bit0 categorical, bit1 default_left, bits2-3 missing
        # type (0 None, 4 Zero, 8 NaN) — upstream tree.h encoding
        dl = (np.asarray(tree.split_default_left[:n_splits]).astype(bool)
              & ~is_cat)  # default-left bit is numeric-only upstream
        mt = np.asarray(tree.split_missing_type[:n_splits]).astype(int)
        dec = (is_cat.astype(int) | (dl.astype(int) << 1)
               | (np.clip(mt, 0, 2) << 2))
        thr_out = thr.astype(np.float64).copy()
        cat_boundaries = [0]
        cat_words: list = []
        bm = tree.split_mask.shape[-1]
        n_words = max((bm + 31) // 32, 1)
        ci = 0
        for s in range(n_splits):
            if not is_cat[s]:
                continue
            thr_out[s] = ci
            mask = np.asarray(tree.split_mask[s]).astype(bool)
            words = np.zeros(n_words, np.uint32)
            for c in np.flatnonzero(mask):
                words[c // 32] |= np.uint32(1 << (c % 32))
            cat_words.extend(int(wd) for wd in words)
            cat_boundaries.append(cat_boundaries[-1] + n_words)
            ci += 1
        out.write("split_feature=" + " ".join(map(str, sf)) + "\n")
        out.write("split_gain=" + " ".join(
            f"{g:g}" for g in np.asarray(tree.split_gain[:n_splits])) + "\n")
        out.write("threshold=" + " ".join(f"{t:.17g}" for t in thr_out) + "\n")
        out.write("decision_type=" + " ".join(map(str, dec)) + "\n")
        out.write("left_child=" + " ".join(map(str, lc)) + "\n")
        out.write("right_child=" + " ".join(map(str, rc)) + "\n")
        if num_cat:
            out.write("cat_boundaries=" + " ".join(map(str, cat_boundaries))
                      + "\n")
            out.write("cat_threshold=" + " ".join(map(str, cat_words)) + "\n")
    out.write("leaf_value=" + " ".join(
        f"{v + value_shift:.17g}" for v in lv) + "\n")
    out.write("leaf_count=" + " ".join(
        str(int(round(c))) for c in lcnt) + "\n")
    out.write("shrinkage=1\n\n")
    return out.getvalue()


def _tree_to_json(tree: Tree, thr: np.ndarray, value_shift: float) -> dict:
    """Nested `tree_structure` dict from the slot representation (upstream
    `LGBM_BoosterDumpModel` layout: internal nodes carry split fields +
    left/right_child subdicts, leaves carry leaf_index/value/count). Leaf
    indices are slot ids (slot 0 = root, split s's right child = slot s+1)."""
    valid = np.asarray(tree.split_valid).astype(bool)
    leaf_value = np.asarray(tree.leaf_value, np.float64)
    leaf_count = np.asarray(tree.leaf_count, np.float64)
    missing_names = ("None", "Zero", "NaN")
    root: dict = {"leaf_index": 0}
    leaves = {0: root}
    split_index = 0
    for s in range(len(valid)):
        if not valid[s]:
            continue
        slot = int(np.asarray(tree.split_slot)[s])
        node = leaves.pop(slot)
        node.clear()
        left = {"leaf_index": slot}
        right = {"leaf_index": s + 1}
        is_cat = bool(np.asarray(tree.split_is_cat)[s])
        if is_cat:
            cats = np.flatnonzero(np.asarray(tree.split_mask)[s])
            threshold = "||".join(str(int(c)) for c in cats)
        else:
            threshold = float(thr[s])
        node.update({
            "split_index": split_index,
            "split_feature": int(np.asarray(tree.split_feat)[s]),
            "split_gain": float(np.asarray(tree.split_gain)[s]),
            "threshold": threshold,
            "decision_type": "==" if is_cat else "<=",
            "default_left": bool(np.asarray(tree.split_default_left)[s]),
            "missing_type": missing_names[
                int(np.asarray(tree.split_missing_type)[s]) % 3],
            "left_child": left,
            "right_child": right,
        })
        leaves[slot] = left
        leaves[s + 1] = right
        split_index += 1
    for slot, node in leaves.items():
        node["leaf_value"] = float(leaf_value[slot]) + value_shift
        node["leaf_count"] = int(round(float(leaf_count[slot])))
    return root


# ---------------------------------------------------------------------------
# jit prediction programs
# ---------------------------------------------------------------------------

#: below this many rows, trees traverse in parallel (vmap over the tree
#: axis, one wide kernel — serving-latency shape); above it, a scan over
#: trees accumulates in place (bulk-transform shape, no [T, N] temporary)
_PREDICT_VMAP_MAX_ROWS = 4096


def _raw_predict_impl(trees: Tree, thresholds, init, x, multiclass: bool):
    def one_tree(tree, thr):
        slot = tree_apply_raw(tree, x, thr)
        return tree.leaf_value[slot]

    small = x.shape[0] <= _PREDICT_VMAP_MAX_ROWS  # static at trace time
    if multiclass:
        if small:
            vals = jax.vmap(jax.vmap(one_tree))(trees, thresholds)  # [T,K,N]
            return init[None, :] + vals.sum(axis=0).T               # [N,K]

        def per_iter(acc, tk):
            tree, thr = tk
            return acc + jax.vmap(one_tree)(tree, thr).T, None
        k = trees.split_slot.shape[1]
        acc0 = jnp.broadcast_to(init[None, :],
                                (x.shape[0], k)).astype(jnp.float32)
        out, _ = jax.lax.scan(per_iter, acc0, (trees, thresholds))
        return out
    if small:
        vals = jax.vmap(one_tree)(trees, thresholds)                # [T,N]
        return init + vals.sum(axis=0)

    def per_iter(acc, tk):
        tree, thr = tk
        return acc + one_tree(tree, thr), None
    acc0 = jnp.full((x.shape[0],), init, jnp.float32)
    out, _ = jax.lax.scan(per_iter, acc0, (trees, thresholds))
    return out


def _predict_leaf_impl(trees: Tree, thresholds, x, multiclass: bool):
    def one_tree(tree, thr):
        return tree_apply_raw(tree, x, thr)

    if multiclass:
        return jax.lax.map(lambda tk: jax.vmap(one_tree)(tk[0], tk[1]),
                           (trees, thresholds))
    return jax.lax.map(lambda tk: one_tree(tk[0], tk[1]), (trees, thresholds))


def _raw_predict_jit(trees: Tree, thresholds, init, x, multiclass: bool):
    """Serving-critical margin program, acquired via the shared cached_jit
    registry (compile/): every booster in the process shares one executable
    per (shape, dtype, multiclass) signature, counted in cache_stats."""
    fn = cached_jit(_raw_predict_impl, key="gbdt_raw_predict",
                    name="gbdt_raw_predict", static_argnames=("multiclass",))
    return fn(trees, thresholds, init, x, multiclass=multiclass)


def _predict_leaf_jit(trees: Tree, thresholds, x, multiclass: bool):
    fn = cached_jit(_predict_leaf_impl, key="gbdt_predict_leaf",
                    name="gbdt_predict_leaf",
                    static_argnames=("multiclass",))
    return fn(trees, thresholds, x, multiclass=multiclass)


def _flat_raw_predict(multiclass: bool, *arrays):
    """Flat-argument adapter for jax.export: Tree is a NamedTuple and
    export serialization wants plain positional arrays, so artifacts carry
    ``(*tree_fields, thresholds, init, x)`` flattened in Tree._fields
    order (the loader reassembles identically — a stable calling
    convention independent of pytree registration)."""
    nf = len(Tree._fields)
    trees = Tree(*arrays[:nf])
    thresholds, init, x = arrays[nf], arrays[nf + 1], arrays[nf + 2]
    return _raw_predict_impl(trees, thresholds, init, x, multiclass)
