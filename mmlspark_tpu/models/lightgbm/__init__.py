from .base import LightGBMModelBase, LightGBMParamsBase
from .booster import Booster
from .dataset import LightGBMDataset
from .delegate import LightGBMDelegate
from .classifier import LightGBMClassificationModel, LightGBMClassifier
from .ranker import LightGBMRanker, LightGBMRankerModel
from .regressor import LightGBMRegressionModel, LightGBMRegressor
