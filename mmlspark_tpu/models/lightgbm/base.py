"""Shared LightGBM-style estimator machinery.

Reference analogue: `trait LightGBMBase[M]` (lightgbm/LightGBMBase.scala:20-263) — shared
train(): batch splitting, column casting, partition prep, driver rendezvous, mapPartitions
training, booster reduce — and the param traits (lightgbm/LightGBMParams.scala:12-378).

TPU-native restructure: "partition prep + rendezvous + mapPartitions + reduce" collapses
into: bin on host -> shard rows over the device mesh -> ONE jit/shard_map training program
whose histogram psum rides ICI -> replicated Booster arrays come back on every shard
(no reduce step needed; the reference's `.reduce((b,_)=>b)` at LightGBMBase.scala:228-230
picked an arbitrary worker's copy of an identical model, which replication gives us for free).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...compile import cache as compilecache
from ...core.dataframe import DataFrame, dense_matrix
from ...core import params as _p
from ...core.pipeline import Estimator, Model
from ...ops.binning import BinMapper
from ...ops.boosting import (BoostResult, GBDTConfig, HParams, Tree,
                             make_train_fn)
from ...parallel import mesh as meshlib
from ...parallel import multihost as mhlib
from ...parallel import strategy as stratlib
from ...resilience.elastic import (CheckpointStore, Preempted,
                                   PreemptionDrain)
from ...utils.profiling import NULL_TIMELINE, FitTimeline
from .booster import Booster, concat_boosters

Param = _p.Param

import contextlib
import copy
import functools


@functools.lru_cache(maxsize=64)
def _compiled_serial(cfg: GBDTConfig):
    """jit programs memoized on the (hashable) config: a second fit with the
    same config + shapes reuses the compiled executable instead of retracing
    a fresh closure (round-1 verdict: warm-up fits never warmed anything).
    Routed through compile/cached_jit so hits/misses/compile-seconds land in
    cache_stats and recompiles resolve via the persistent XLA cache."""
    train = make_train_fn(cfg)
    return (compilecache.cached_jit(train, key=("gbdt_serial_full", cfg),
                                    name="gbdt_full"),
            compilecache.cached_jit(train.chunk,
                                    key=("gbdt_serial_chunk", cfg),
                                    name="gbdt_chunk"))


def _vmapped_many(call):
    """vmap over (key, HParams) with data (and optional trailing group
    layout) broadcast: `call(binned, y, w, is_train, margin, key, hp,
    *rest)` runs one candidate."""
    def many(binned, y, w, is_train, margin, keys, hp_batch, *rest):
        return jax.vmap(
            lambda k_, hp_: call(binned, y, w, is_train, margin, k_, hp_,
                                 *rest))(keys, hp_batch)
    return many


@functools.lru_cache(maxsize=64)
def _compiled_serial_vmapped(cfg: GBDTConfig, grouped: bool = False):
    """One compiled program training a BATCH of continuous-hyperparameter
    candidates: vmap over (key, HParams), data (and the lambdarank group
    layout, when present) broadcast. The TPU-first realization of the
    reference's Estimator.fit(dataset, paramMaps) (SparkML surface;
    TuneHyperparameters' thread-pool becomes a single batched XLA
    program).

    split_scan='compact' degrades to 'full' here: under vmap, its
    lax.switch bucket ladder lowers to executing EVERY branch and
    selecting, which is slower than the full scan it replaces. Trees are
    identical either way."""
    if cfg.split_scan == "compact":
        cfg = cfg._replace(split_scan="full")
    train = make_train_fn(cfg)

    def call(b, y, w, t, mg, k_, hp_, *rest):
        return train(b, y, w, t, mg, k_,
                     group_idx=rest[0] if rest else None, hp=hp_)

    return compilecache.cached_jit(
        _vmapped_many(call), key=("gbdt_serial_vmapped", cfg, grouped),
        name="gbdt_vmapped")


@functools.lru_cache(maxsize=64)
def _compiled_sharded_vmapped(cfg: GBDTConfig, ndev: int,
                              grouped: bool = False):
    """Vmapped candidate batch over the shard_map'd trainer: data sharded
    over the mesh axis, HParams batched over vmap — B candidates x D shards
    in one program. `grouped` threads the lambdarank group layout (sharded
    like the rows). split_scan='compact' degrades to 'full' here (see
    _compiled_serial_vmapped)."""
    if cfg.split_scan == "compact":
        cfg = cfg._replace(split_scan="full")
    m = meshlib.get_mesh(ndev)
    axis = meshlib.DATA_AXIS
    train = make_train_fn(cfg)
    specs = (P(axis),) * 5 + (P(), P()) + ((P(axis),) if grouped else ())
    sharded = meshlib.shard_map(
        lambda b, y, w, t, mg, k_, hp_, *rest: train(
            b, y, w, t, mg, k_,
            group_idx=rest[0] if rest else None, hp=hp_),
        mesh=m, in_specs=specs, out_specs=P(), check_vma=False)

    return compilecache.cached_jit(
        _vmapped_many(sharded),
        key=("gbdt_sharded_vmapped", cfg, ndev, grouped),
        name="gbdt_sharded_vmapped")


@functools.lru_cache(maxsize=64)
def _compiled_sharded(cfg: GBDTConfig, ndev: int, grouped: bool):
    m = meshlib.get_mesh(ndev)
    axis = meshlib.DATA_AXIS
    train = make_train_fn(cfg)
    dart = cfg.boosting_type == "dart"
    gspec = (P(axis),) if grouped else ()
    full = meshlib.shard_map(
        train, mesh=m, in_specs=(P(axis),) * 5 + (P(),) + gspec,
        out_specs=P(), check_vma=False)

    def chunk_fn(b, y, w, t, mg, k_, s_, sc, lr, *rest):
        # positional tail: [deltas, tree_scale] (dart) then [group_idx]
        rest = list(rest)
        dl = ts = None
        if dart:
            dl, ts = rest[0], rest[1]
            rest = rest[2:]
        return train.chunk(b, y, w, t, mg, k_, s_, sc, lr,
                           group_idx=rest[0] if rest else None,
                           deltas_in=dl, tree_scale_in=ts)

    # dart's deltas [T, N, K] shard with the rows on axis 1; tree_scale
    # and the carried PRNG key are replicated
    dspec = (P(None, axis), P()) if dart else ()
    chunk = meshlib.shard_map(
        chunk_fn, mesh=m,
        in_specs=(P(axis),) * 5 + (P(), P(), P(axis), P()) + dspec + gspec,
        out_specs=(P(), P(), P(), P(axis), P()) + dspec + (P(),),
        check_vma=False)
    return (compilecache.cached_jit(
                full, key=("gbdt_sharded_full", cfg, ndev, grouped),
                name="gbdt_sharded_full"),
            compilecache.cached_jit(
                chunk, key=("gbdt_sharded_chunk", cfg, ndev, grouped),
                name="gbdt_sharded_chunk"))


@compilecache.on_clear
def _clear_compiled_factories() -> None:
    # the lru memos above hold cached_jit wrappers: clearing the compile
    # registry must clear them too, or they keep handing back wrappers
    # whose executables jax.clear_caches() already dropped
    _compiled_serial.cache_clear()
    _compiled_serial_vmapped.cache_clear()
    _compiled_sharded_vmapped.cache_clear()
    _compiled_sharded.cache_clear()


class LightGBMParamsBase(Estimator, _p.HasFeaturesCol, _p.HasLabelCol,
                         _p.HasPredictionCol, _p.HasWeightCol,
                         _p.HasValidationIndicatorCol, _p.HasInitScoreCol):
    """Param surface mirroring lightgbm/LightGBMParams.scala (names kept)."""

    boostingType = Param("boostingType", "gbdt, rf, dart or goss", "gbdt")
    numIterations = Param("numIterations", "number of boosting iterations", 100, int)
    learningRate = Param("learningRate", "shrinkage rate", 0.1, float)
    numLeaves = Param("numLeaves", "max leaves per tree", 31, int)
    maxBin = Param("maxBin", "max feature bins", 255, int)
    binSampleCount = Param("binSampleCount",
                           "rows sampled for quantile bin edges", 200000, int)
    baggingFraction = Param("baggingFraction", "row subsample fraction", 1.0, float)
    posBaggingFraction = Param("posBaggingFraction",
                               "positive-class bagging fraction (binary; "
                               "<0 = follow baggingFraction)", -1.0, float)
    negBaggingFraction = Param("negBaggingFraction",
                               "negative-class bagging fraction (binary; "
                               "<0 = follow baggingFraction)", -1.0, float)
    baggingFreq = Param("baggingFreq", "bagging frequency (0=off)", 0, int)
    baggingSeed = Param("baggingSeed", "bagging seed", 3, int)
    boostFromAverage = Param("boostFromAverage",
                             "start boosting from the label mean "
                             "(upstream boost_from_average)", True)
    maxDeltaStep = Param("maxDeltaStep",
                         "cap on |leaf output| before shrinkage; 0 = off "
                         "(upstream max_delta_step)", 0.0, float)
    maxBinByFeature = Param("maxBinByFeature",
                            "per-feature bin budgets (list of ints, <= "
                            "maxBin; empty = all features use maxBin)", None)
    improvementTolerance = Param(
        "improvementTolerance",
        "early-stopping tolerance: validation metric counts as improved when "
        "score - best < tolerance (TrainUtils.scala:287-298 comparator)", 0.0,
        float)
    featureFraction = Param("featureFraction", "feature subsample per tree", 1.0,
                            float)
    maxDepth = Param("maxDepth", "max tree depth (<=0 = unlimited)", -1, int)
    minSumHessianInLeaf = Param("minSumHessianInLeaf",
                                "min sum of hessians per leaf", 1e-3, float)
    minDataInLeaf = Param("minDataInLeaf", "min rows per leaf", 20, int)
    lambdaL1 = Param("lambdaL1", "L1 regularization", 0.0, float)
    lambdaL2 = Param("lambdaL2", "L2 regularization", 0.0, float)
    minGainToSplit = Param("minGainToSplit", "min split gain", 0.0, float)
    earlyStoppingRound = Param("earlyStoppingRound",
                               "stop if no valid improvement in N rounds (0=off)",
                               0, int)
    topRate = Param("topRate", "goss top gradient keep rate", 0.2, float)
    otherRate = Param("otherRate", "goss small-gradient sample rate", 0.1, float)
    dropRate = Param("dropRate", "dart: fraction of prior iterations dropped "
                     "per boosting round (LightGBM drop_rate)", 0.1, float)
    skipDrop = Param("skipDrop", "dart: probability of skipping dropout for "
                     "an iteration (LightGBM skip_drop)", 0.5, float)
    objective = Param("objective", "training objective", "regression")
    modelString = Param("modelString", "serialized warm-start model", "")
    numBatches = Param("numBatches",
                       "split training into sequential batches "
                       "(LightGBMBase.scala:28-50)", 0, int)
    verbosity = Param("verbosity", "log verbosity", -1, int)
    seed = Param("seed", "random seed", 0, int)
    # distribution controls — mesh-native replacements for executor params
    numTasks = Param("numTasks",
                     "number of data shards (devices); 0 = all devices "
                     "(ClusterUtil replacement)", 0, int)
    parallelism = Param("parallelism",
                        "tree learner: 'auto' (default — sharded fit "
                        "whenever >1 device is visible, data_parallel vs "
                        "voting_parallel chosen per (n_features, bins, "
                        "topK) from the dryrun-validated closed-form comm "
                        "model, parallel/strategy.py), 'data'/"
                        "'data_parallel', 'voting'/'voting_parallel', or "
                        "'off'/'serial' (one device; the reference names "
                        "from LightGBMExecutionParams.parallelism stay "
                        "accepted)", "auto")
    topK = Param("topK",
                 "voting_parallel top-k voted features per leaf; larger is "
                 "more accurate but allreduces more histogram traffic "
                 "(LightGBMConstants.DefaultTopK)", 20, int)
    useBarrierExecutionMode = Param(
        "useBarrierExecutionMode",
        "compat no-op: SPMD launch is inherently gang-scheduled", False)
    defaultListenPort = Param("defaultListenPort",
                              "compat no-op: no socket rendezvous on TPU", 12400,
                              int)
    driverListenPort = Param("driverListenPort",
                             "compat no-op: no driver rendezvous on TPU", 0,
                             int)
    timeout = Param("timeout", "compat no-op socket timeout", 120.0, float)
    histMethod = Param("histMethod",
                       "histogram kernel: auto | autotune (measured) | onehot | scatter | pallas",
                       "auto")
    histChunk = Param("histChunk", "rows per histogram chunk", 512, int)
    metric = Param("metric",
                   "evaluation metric ('' = objective default): l1/mae, "
                   "l2/mse, rmse, mape, auc, auc_exact, binary_logloss, "
                   "binary_error, multi_logloss, multi_error, ndcg "
                   "(LightGBMParams.scala:310-342); auc/ndcg are reported "
                   "as 1 - value (lower-is-better convention). Distributed "
                   "'auc' is binned (documented bound); 'auc_exact' "
                   "all_gathers scores for exact rank AUC at O(N) traffic "
                   "per eval (serial fits are always exact)", "")
    isProvideTrainingMetric = Param(
        "isProvideTrainingMetric",
        "compat: per-iteration train metrics are always computed here and "
        "surfaced on the fitted model / delegate measures", False)
    histDtype = Param("histDtype",
                      "MXU operand dtype for the histogram contraction: "
                      "bf16 (fast, grads rounded ~3 digits) or f32 (exact, "
                      "bit-reproducible vs the scatter oracle)", "bf16")
    useMissing = Param(
        "useMissing",
        "reserve a missing bin for NaN-containing features and LEARN the "
        "split default direction (upstream use_missing); False = legacy "
        "NaN-to-lowest-bin behavior", True, bool)
    histRefresh = Param(
        "histRefresh",
        "histogram refresh policy: eager (exact LightGBM leaf-wise, one "
        "all-slots pass per split) or lazy (split best-first among leaves "
        "with current histograms, re-histogram only when that pool dries — "
        "~one pass per tree level, new children enter the pool one refresh "
        "late; TPU-native optimization, no reference analogue)", "eager")
    histScan = Param(
        "histScan",
        "per-split histogram construction (eager refresh only): full (one "
        "all-slots pass over every row per split) or compact (rows kept "
        "partitioned by leaf; each split histograms only the parent's "
        "segment in a pow2-bucketed masked 2-slot pass — the TPU analogue "
        "of upstream's DataPartition + smaller-child trick, exact leaf-wise "
        "semantics at ~N*depth instead of N*(L-1) histogram work)", "full")
    splitsPerPass = Param(
        "splitsPerPass",
        "batched leaf-wise growth: apply the top-k best splits (necessarily "
        "on distinct leaves, so their gains are mutually independent) per "
        "histogram pass, then refresh every new child in ONE all-slots "
        "pass. 1 = strict leaf-wise (exact LightGBM split order); k>1 cuts "
        "histogram passes per tree from numLeaves-1 to ~(numLeaves-1)/k at "
        "the cost that children created within a pass cannot compete until "
        "the next pass. Gains are never stale (unlike histRefresh='lazy'). "
        "eager/full only", 1, int)
    fitPipeline = Param(
        "fitPipeline",
        "host/device fit pipeline: 'auto' (pipelined dataset construction "
        "at >= 2M float32 rows — binning of row-block k+1 overlaps block "
        "k's async device transfer, label/weight/margin transfers ride "
        "under the first blocks, and the itersPerCall chunk loop "
        "dispatches chunk i+1 before fetching chunk i's host "
        "bookkeeping), 'on' (force the pipeline at any size/dtype — with "
        "collectFitTimings this records a barrier-free FitTimeline with "
        "per-block bin/put spans and a measured overlap ratio instead of "
        "the phase-separated decomposition), or 'off' (sequential "
        "construction; with collectFitTimings this is the separable-phase "
        "decomposition mode). Sharded fits stream per-shard "
        "double-buffered blocks placed with the mesh row sharding (each "
        "device's transfers overlap the next block's binning); the "
        "grouped lambdarank layout keeps one-shot placement. Boosters "
        "are BIT-IDENTICAL across all three (regression-pinned incl. NaN "
        "and float64-fallback inputs)",
        "auto")
    collectFitTimings = Param(
        "collectFitTimings",
        "record a wall-time decomposition of fit() — binning, device "
        "transfer, boosting, model assembly — onto the fitted model as "
        "`model.fit_timings` (the VW TrainingStats diagnostics analogue, "
        "VowpalWabbitBase.scala:268-303). Adds device barriers between "
        "phases, so leave False when benchmarking end-to-end wall",
        False, bool)
    checkpointDir = Param(
        "checkpointDir",
        "directory for preemption-safe elastic training: at every "
        "compiled-chunk boundary the booster-so-far is written as a "
        "durable snapshot (atomic write-to-temp + fsync + rename, native "
        "text payload + a JSON manifest recording the content digest, "
        "tree count, device count and batch index; keep-last-K retention "
        "via checkpointKeepLast — resilience/elastic.CheckpointStore). A "
        "later fit() with the same checkpointDir resumes from the newest "
        "digest-valid snapshot — a corrupt/truncated newest snapshot "
        "falls back to the previous one instead of crashing or silently "
        "training from scratch — and trains only the REMAINING "
        "iterations of the in-flight batch (total stays numIterations "
        "per batch; the manifest's batch_index resumes numBatches>1 "
        "fits mid-batch). The resume is ELASTIC: booster state is "
        "replicated, so a snapshot written at ndev=N restores at ndev=M "
        "— rows re-shard through parallel/mesh.shard_rows at the current "
        "device count (docs/RESILIENCE.md contract). While the fit runs, "
        "SIGTERM/SIGINT triggers a preemption drain (finish the "
        "in-flight chunk, snapshot, raise resilience.Preempted within "
        "drainGraceS). Snapshots are removed on successful completion. "
        "Early-stopping counters and bagging keys (and the fit's PRNG "
        "stream, which restarts from the seed) restart at the resume "
        "point; with bagging off, resumed trees equal the uninterrupted "
        "fit's. Delegate hooks and delegate-driven learning-rate "
        "schedules see ABSOLUTE iteration indices (a resume continues at "
        "the checkpointed tree count; completed batches' hooks are not "
        "replayed). Combine with itersPerCall to bound the work lost to "
        "an interruption. Not supported with dart (resume needs the "
        "[T,N,K] dropout delta history — device training state a booster "
        "snapshot's manifest does not carry) or fit(df, paramMaps)", None)
    checkpointKeepLast = Param(
        "checkpointKeepLast",
        "snapshots retained in checkpointDir (keep-last-K retention). "
        "Keep >= 2: the corrupt-newest fallback needs a previous "
        "snapshot to restore from", 2, int)
    drainGraceS = Param(
        "drainGraceS",
        "preemption-drain grace budget (seconds): after SIGTERM/SIGINT "
        "the fit finishes the in-flight chunk and writes the snapshot; "
        "if that cannot complete within the grace, the drain watchdog "
        "hard-exits (status 75) before the pool's SIGKILL can land "
        "mid-write. None (default) resolves the fleet-wide "
        "MMLSPARK_TPU_DRAIN_GRACE_S env var, falling back to 30 s. Size "
        "itersPerCall so one chunk always fits inside the pool's kill "
        "grace", None)
    itersPerCall = Param(
        "itersPerCall",
        "split training into device programs of at most this many boosting "
        "iterations, carrying raw scores, the PRNG key, and (dart) the "
        "dropout delta/rescale state between calls — BIT-IDENTICAL to the "
        "one-program fit for every boosting mode. 0 = one program for the "
        "whole fit. Bounds single-device-call duration: shared TPU pools "
        "kill programs that hold the chip for minutes (measured: an 11M-row "
        "x 100-iter eager program is evicted; 4 x 25 survives)", 0, int)
    slotNames = Param("slotNames", "feature slot names", None)
    categoricalSlotIndexes = Param("categoricalSlotIndexes",
                                   "indexes of categorical features", None)
    categoricalSlotNames = Param("categoricalSlotNames",
                                 "names of categorical features", None)
    catSmooth = Param("catSmooth",
                      "categorical split smoothing (LightGBM cat_smooth)", 10.0,
                      float)
    maxCatThreshold = Param("maxCatThreshold",
                            "max categories on one split side", 32, int)
    alpha = Param("alpha", "quantile/huber alpha", 0.9, float)
    tweedieVariancePower = Param("tweedieVariancePower",
                                 "tweedie variance power in (1,2)", 1.5, float)
    # prediction-output params (LightGBMPredictionParams trait in
    # LightGBMParams.scala) — propagated onto the fitted model
    leafPredictionCol = Param(
        "leafPredictionCol",
        "output column for per-tree leaf indices (empty = off)", "")
    featuresShapCol = Param(
        "featuresShapCol",
        "output column for SHAP contributions (empty = off)", "")
    delegate = Param(
        "delegate",
        "LightGBMDelegate with before/after batch + iteration hooks and "
        "dynamic learning rate (LightGBMDelegate.scala:1-60); forces chunked "
        "host-driven training", None, complex=True)

    def _propagate_model_params(self, model):
        for p in ("featuresCol", "predictionCol", "leafPredictionCol",
                  "featuresShapCol"):
            if p in model.params():
                model.set(p, self.get(p))
        return model

    # ------------------------------------------------------------------ fit
    def _objective_name(self) -> str:
        return self.get("objective")

    def _num_class(self, y: np.ndarray) -> int:
        return 1

    def _extract_features(self, df: DataFrame) -> np.ndarray:
        x = df[self.get("featuresCol")]
        if hasattr(x, "toarray") and hasattr(x, "tocsr"):
            # sparse matrix column (kept sparse by the DataFrame): the GBDT
            # device plane is dense binned uint8, so densify here — the
            # reference's CSR marshalling boundary
            # (LightGBMUtils.scala:201-265). Wide sparse refuses with a
            # pointer at featurize.SparseFeatureBundler.
            x = dense_matrix(x)
        elif x.dtype == object and len(x) and hasattr(x[0], "toarray"):
            # per-row scipy sparse vectors (the reference's sparse dataset
            # path, LightGBMUtils.scala:201-265) densify at ingestion
            x = np.vstack([np.asarray(r.toarray(), np.float32).ravel()
                           for r in x])
        x = np.asarray(x, np.float32)
        if x.ndim != 2:
            raise ValueError("featuresCol must be a 2-D vector column")
        return x

    def _bin_config(self) -> tuple:
        """The parameters that determine binning — frozen by
        LightGBMDataset at construction (upstream Dataset contract), and
        the SINGLE source _fit_binning builds the BinMapper from, so the
        frozen-config equality check can never drift from what binning
        actually consumes."""
        mbbf = self.get("maxBinByFeature")
        if mbbf is None or len(mbbf) == 0:
            mbbf_t = ()
        else:
            mbbf_t = tuple(int(v) for v in mbbf)
        return (int(self.get("maxBin")), int(self.get("binSampleCount")),
                int(self.get("seed")), tuple(self._categorical_indexes()),
                mbbf_t, bool(self.get("useMissing")))

    def _fit_bin_mapper(self, x: np.ndarray) -> BinMapper:
        max_bin, sample_count, seed, cat, mbbf, use_missing = \
            self._bin_config()
        return BinMapper.fit(x, max_bin, sample_count, seed, categorical=cat,
                             max_bins_by_feature=(
                                 np.asarray(mbbf, np.int64) if mbbf
                                 else None),
                             use_missing=use_missing)

    def _fit_bin_mapper_store(self, store) -> BinMapper:
        """`_fit_bin_mapper` for an on-disk shard store: edges from a
        bounded gathered row sample + the manifest's exact whole-pass
        stats — same `_bin_config` source, bit-identical mapper to
        BinMapper.fit on the materialized matrix (digest parity)."""
        from ...io import shardstore as sstore
        max_bin, sample_count, seed, cat, mbbf, use_missing = \
            self._bin_config()
        return sstore.fit_bin_mapper(
            store, max_bin, sample_count, seed, categorical=cat,
            max_bins_by_feature=(np.asarray(mbbf, np.int64) if mbbf
                                 else None),
            use_missing=use_missing)

    @staticmethod
    def _missing_idx_of(bm: BinMapper):
        # features with a reserved missing bin get both-direction split scans
        return tuple(int(j) for j in np.nonzero(bm.missing)[0])

    def _fit_binning(self, x: np.ndarray):
        """Fit the bin mapper + transform to the binned uint8 matrix —
        the LGBM_DatasetCreateFromMat equivalent; hoisted so
        LightGBMDataset can run it once for many fits."""
        bm = self._fit_bin_mapper(x)
        return bm, bm.transform(x), self._missing_idx_of(bm)

    @staticmethod
    def _binned_to_device(bm: BinMapper, x: np.ndarray,
                          blk: Optional[int] = None, timeline=None):
        """Row-block pipelined dataset construction: bin block k+1 on the
        host while block k's int8 copy rides to the device (device_put is
        async) — overlaps the two serial halves of
        LGBM_DatasetCreateFromMat's role instead of paying
        binning + transfer back to back. Double-buffered by construction:
        at most two blocks are in flight (the host-side array being binned
        plus the previous block's async transfer; JAX pins the source
        buffer until its copy lands, so no staging reuse and no wait).
        Blocks land in ONE preallocated device buffer through a donated
        dynamic_update_slice, so peak HBM stays ~1x the binned matrix +
        one block (a naive concatenate of parts would double it at exactly
        the scale this path targets). This stage contains NO host sync —
        the only commit barrier is at first-dispatch time (sync-point
        lint, tests/test_fit_pipeline.py); `timeline` (a FitTimeline)
        records the per-block bin/put spans without adding barriers."""
        tl = timeline if timeline is not None else NULL_TIMELINE
        n, fdim = x.shape
        if blk is None:
            blk = max(1_000_000, -(-n // 8))
        tl.meta["blk"] = int(min(blk, n))
        tl.meta["n_blocks"] = 1 + len(range(blk, n, blk))
        with tl.span("bin[0]"):
            b0 = bm.transform(x[:blk])
        with tl.span("put[0]"):
            first = jax.device_put(b0)
        if blk >= n:
            return first
        buf = jnp.zeros((n, fdim), first.dtype)
        write = compilecache.cached_jit(
            lambda buf, block, i0: jax.lax.dynamic_update_slice(
                buf, block, (i0, 0)),
            key="binned_write2d", name="gbdt_binned_write", donate_argnums=0)
        buf = write(buf, first, jnp.int32(0))
        for i0 in range(blk, n, blk):
            # the final window shifts back to stay full-size (ONE compiled
            # write shape); its overlap rows re-bin to identical values
            j0 = min(i0, n - blk)
            with tl.span(f"bin[{j0}]"):
                bk = bm.transform(x[j0:j0 + blk])
            with tl.span(f"put[{j0}]"):
                buf = write(buf, jax.device_put(bk), jnp.int32(j0))
        return buf

    @staticmethod
    def _binned_to_device_sharded(bm: BinMapper, x: np.ndarray, mesh,
                                  blk: Optional[int] = None, timeline=None):
        """Sharded row-block pipelined dataset construction — the
        _binned_to_device double-buffering composed with the device mesh.

        Layout: the padded row space is viewed as [ndev, rows_per_dev, F]
        (device d owns the contiguous global rows [d*ppd, (d+1)*ppd) —
        plain row order, same digests as the one-shot placement). Block j
        is the SUPER-BLOCK of every device's rows [j0, j0+blk): binned on
        host as one [ndev*blk, F] transform, then device_put with a
        (data, None, None) NamedSharding — one async dispatch whose
        per-device pieces ride each device's host link in parallel, so
        every shard's transfer overlaps the next super-block's binning.
        The donated dynamic_update_slice writes at (0, j0, 0): offset 0 on
        the SHARDED axis, so every write is shard-local (no collective
        rides the assembly). The final reshape back to [N, F] merges the
        two leading axes shard-contiguously — also communication-free.
        No host sync anywhere (sync-point lint, tests/test_fit_pipeline).

        Multi-host fits (jax.process_count() > 1) route to
        parallel/multihost.binned_to_device: the same double-buffered
        streaming with each HOST binning and transferring only its own
        row spans, assembled into one global array via
        jax.make_array_from_single_device_arrays — a committed-to-
        global-sharding device_put is not valid across processes."""
        if meshlib.process_count() > 1:
            return mhlib.binned_to_device(bm, x, mesh, blk=blk,
                                          timeline=timeline)
        tl = timeline if timeline is not None else NULL_TIMELINE
        nd = mesh.shape[meshlib.DATA_AXIS]
        x, _ = meshlib.pad_to_multiple(np.ascontiguousarray(x), nd)
        n, fdim = x.shape
        ppd = n // nd
        if blk is None:
            blk = max(1_000_000 // nd, -(-ppd // 8))
        blk = max(1, min(blk, ppd))
        tl.meta["blk"] = int(blk * nd)
        tl.meta["n_blocks"] = 1 + len(range(blk, ppd, blk))
        tl.meta["ndev"] = int(nd)
        xv = x.reshape(nd, ppd, fdim)
        sh3 = jax.sharding.NamedSharding(
            mesh, P(meshlib.DATA_AXIS, None, None))
        flat = compilecache.cached_jit(
            lambda b: b.reshape(b.shape[0] * b.shape[1], b.shape[2]),
            key=("binned_flat", nd), name="gbdt_binned_flat",
            out_shardings=meshlib.data_sharding(mesh, 2))

        def bin_block(j0):
            return bm.transform(
                xv[:, j0:j0 + blk].reshape(-1, fdim)).reshape(nd, blk, fdim)

        with tl.span("bin[0]"):
            b0 = bin_block(0)
        with tl.span("put[0]"):
            first = jax.device_put(b0, sh3)
        if blk >= ppd:
            return flat(first)
        buf = jnp.zeros((nd, ppd, fdim), first.dtype, device=sh3)
        write = compilecache.cached_jit(
            lambda buf, block, j0: jax.lax.dynamic_update_slice(
                buf, block, (0, j0, 0)),
            key="binned_write3d", name="gbdt_binned_write", donate_argnums=0)
        buf = write(buf, first, jnp.int32(0))
        for i0 in range(blk, ppd, blk):
            # the final window shifts back to stay full-size (ONE compiled
            # write shape); its overlap rows re-bin to identical values
            j0 = min(i0, ppd - blk)
            with tl.span(f"bin[{j0}]"):
                bk = bin_block(j0)
            with tl.span(f"put[{j0}]"):
                buf = write(buf, jax.device_put(bk, sh3), jnp.int32(j0))
        return flat(buf)

    def _pipelined_device_data(self, bm: BinMapper, x: np.ndarray, y, w,
                               is_valid, margin, has_init: bool, k: int,
                               groups, timeline, mesh=None):
        """The pipelined construction stage of the host/device fit
        pipeline: every fixed host cost is dispatched ASYNC before the
        row-block loop so it rides the interconnect UNDER the first
        blocks' host binning — label/weight/validity transfers, the margin
        copy (device-side zeros when there is no init score: a [N, K]
        zeros transfer is pure waste), and the lambdarank group layout.
        Returns (binned_device, (y_d, w_d, t_d, mg_d, gidx)). No host
        sync anywhere in this stage (sync-point lint): the commit barrier
        is first-dispatch time — in collectFitTimings mode, an explicit
        measured `commit_wait` in _train_booster_once.

        ``mesh``: the sharded variant. Aux arrays ride shard_rows (row
        padding to the data-axis extent, NamedSharding placement, padded
        rows folded to zero weight through the mask product), the binned
        matrix streams through _binned_to_device_sharded's per-shard
        double-buffered blocks, and the returned arrays are global
        row-sharded jax.Arrays ready for the shard_map training program."""
        n = x.shape[0]
        with timeline.span("aux_dispatch"):
            gidx = None
            if mesh is None:
                y_d = jnp.asarray(y)
                w_d = jnp.asarray(w)
                t_d = jnp.asarray((~is_valid).astype(np.float32))
                mg_d = (jnp.asarray(margin) if has_init
                        else jnp.zeros((n, k), jnp.float32))
                if groups is not None:
                    from ...ops.ranking import make_group_layout
                    gidx = jnp.asarray(make_group_layout(groups).group_idx)
            else:
                # the canonical sharded layout: pad + NamedSharding
                # placement + zero-weight fold all live in shard_rows
                # (sharded fits match the serial path's y-as-f64 cast)
                nd = mesh.shape[meshlib.DATA_AXIS]
                n_pad = n + ((-n) % nd)
                if has_init:
                    y_d, t_d, mg_d, w_d, _mask = meshlib.shard_rows(
                        mesh, y.astype(np.float64),
                        (~is_valid).astype(np.float32), margin, weights=w)
                else:
                    # [N, K] zeros never cross the host link: the margin
                    # is EXCLUDED from the transfer set and replaced by
                    # uncommitted device zeros, resharded free at dispatch
                    # (multi-host: per-device zeros assembled into a
                    # global row-sharded array — a single-device
                    # committed zeros is invalid across processes)
                    y_d, t_d, w_d, _mask = meshlib.shard_rows(
                        mesh, y.astype(np.float64),
                        (~is_valid).astype(np.float32), weights=w)
                    mg_d = (mhlib.zeros_row_sharded(mesh, (n_pad, k))
                            if meshlib.process_count() > 1
                            else jnp.zeros((n_pad, k), jnp.float32))
        # forced-on fits pipeline at any size (>= 2 blocks whenever the
        # data allows), auto keeps the measured 4M-scale block size
        if mesh is not None:
            nd = mesh.shape[meshlib.DATA_AXIS]
            # forced-on: ~1024 global rows per super-block floor (the
            # serial 'on' floor split over the shards), >= 2 blocks
            # whenever the per-shard row count allows
            blk = (max(1024 // nd, -(-n_pad // (8 * nd)))
                   if self.get("fitPipeline") == "on" else None)
            binned = self._binned_to_device_sharded(bm, x, mesh, blk=blk,
                                                    timeline=timeline)
        else:
            blk = (max(1024, -(-n // 8)) if self.get("fitPipeline") == "on"
                   else None)
            binned = self._binned_to_device(bm, x, blk=blk,
                                            timeline=timeline)
        return binned, (y_d, w_d, t_d, mg_d, gidx)

    def _extract_xyw(self, df: DataFrame
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray, Optional[np.ndarray]]:
        from .dataset import LightGBMDataset
        self._prebinned = None
        if isinstance(df, LightGBMDataset):
            x, self._prebinned = df.pack_for(self)
            df = df.dataframe
        else:
            x = self._extract_features(df)
        y = np.asarray(df[self.get("labelCol")])
        wcol = self.get("weightCol")
        w = (np.asarray(df[wcol], np.float32) if wcol and wcol in df
             else np.ones(len(df), np.float32))
        vcol = self.get("validationIndicatorCol")
        is_valid = (np.asarray(df[vcol]).astype(bool)
                    if vcol and vcol in df else np.zeros(len(df), bool))
        icol = self.get("initScoreCol")
        init_score = (np.asarray(df[icol], np.float32)
                      if icol and icol in df else None)
        return x, y, w, is_valid, init_score

    #: reference metric aliases (LightGBMParams.scala:310-342)
    _METRIC_ALIASES = {
        "mae": "l1", "mean_absolute_error": "l1", "regression_l1": "l1",
        "mse": "l2", "mean_squared_error": "l2", "regression_l2": "l2",
        "regression": "l2", "root_mean_squared_error": "rmse",
        "l2_root": "rmse", "mean_absolute_percentage_error": "mape",
        "binary": "binary_logloss", "multiclass": "multi_logloss",
        "softmax": "multi_logloss", "lambdarank": "ndcg",
    }
    _METRICS_BY_KIND = {
        "binary": ("auc", "auc_exact", "binary_logloss",
                   "binary_error"),
        "multiclass": ("multi_logloss", "multi_error"),
        "regression": ("l1", "l2", "rmse", "mape"),
        "ranking": ("ndcg",),
    }

    def _resolve_metric(self, objective: str, num_class: int) -> str:
        raw = (self.get("metric") or "").strip().lower()
        if raw in ("", "none", "na", "null", "custom"):
            return ""
        name = self._METRIC_ALIASES.get(raw, raw)
        kind = ("ranking" if objective == "lambdarank"
                else "multiclass" if num_class > 1
                else "binary" if objective == "binary" else "regression")
        allowed = self._METRICS_BY_KIND[kind]
        if name not in allowed:
            raise ValueError(
                f"metric {raw!r} is not valid for objective {objective!r}; "
                f"allowed: {allowed} (or '' for the objective default)")
        return name

    #: estimator param -> HParams field for the vmapped fit(df, paramMaps)
    #: path; any other key in a param map falls back to sequential fits
    _VMAP_PARAM_FIELDS = {
        "learningRate": "learning_rate", "lambdaL1": "lambda_l1",
        "lambdaL2": "lambda_l2", "minGainToSplit": "min_gain_to_split",
        "minSumHessianInLeaf": "min_sum_hessian_in_leaf",
        "minDataInLeaf": "min_data_in_leaf",
        "baggingFraction": "bagging_fraction"}

    def _supports_vmap_fit(self) -> bool:
        return True

    def fit(self, df: DataFrame, params=None):
        """SparkML Estimator.fit surface: `params` may be a single dict (one
        overridden fit) or a LIST of param maps, returning one model per map
        (Estimator.fit(dataset, paramMaps) — the surface TuneHyperparameters
        sweeps, automl/TuneHyperparameters.scala:37-203). Maps touching only
        continuous hyperparameters train in ONE vmapped XLA program.

        `df` may also be a shard-store directory path (or an opened
        `io.shardstore.ShardStore`): the fit then streams the dataset
        from disk with bounded host memory instead of materializing it
        (the out-of-core route, docs/DATA.md)."""
        try:
            from ...io.shardstore import as_store
            store = as_store(df)
            if store is not None:
                if isinstance(params, (list, tuple)):
                    raise ValueError(
                        "fit(store, paramMaps) is not supported for "
                        "shard-store input (the vmapped sweep batches "
                        "in-memory candidates); run one fit per map")
                est = self.copy(params) if params else self
                return est._fit_from_store(store)
            if isinstance(params, (list, tuple)):
                return self.fit_param_maps(df, list(params))
            return super().fit(df, params)
        finally:
            # a failure between _extract_xyw and _train_booster (e.g. a
            # param-validation ValueError) must not leave the estimator
            # pinning a LightGBMDataset's feature/binned matrices
            self._prebinned = None

    # ------------------------------------------------- out-of-core fit
    def _store_fit_spec(self, store):
        """(objective, num_class, groups) for a shard-store fit — the
        per-estimator decisions the in-memory `_fit` derives from full
        label/group arrays, re-derived here from the store manifest's
        exact whole-pass stats (classifier/ranker override)."""
        return self._objective_name(), 1, None

    def _make_store_model(self, booster: Booster):
        """Wrap the trained booster in this estimator's model class
        (the tail of the subclass `_fit`)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support shard-store input")

    def _fit_from_store(self, store) -> "LightGBMModelBase":
        """Out-of-core fit: the dataset never exists in host memory —
        binning samples bounded rows, training arrays stream from disk
        shards through a bounded prefetch ring (io/shardstore.py), and
        checkpoints record a shard cursor so a resume can refuse a
        rewritten store. Digest parity with the in-memory fit is a
        tier-1 contract (tests/test_shardstore.py)."""
        from ...io import shardstore as sstore
        if self.get("numBatches"):
            raise ValueError(
                "numBatches is not supported when fitting from a shard "
                "store (the batch split permutes full row indices); "
                "write per-batch stores instead")
        if self.get("initScoreCol"):
            raise ValueError(
                "initScoreCol is not supported when fitting from a shard "
                "store; warm-start via modelString streams its margin "
                "per block instead")
        if self.get("validationIndicatorCol"):
            raise ValueError(
                "validationIndicatorCol is not supported when fitting "
                "from a shard store (no per-row indicator column on "
                "disk); hold out a separate store for evaluation")
        if self.get("weightCol") and sstore.WEIGHT not in store.columns:
            raise ValueError(
                f"weightCol={self.get('weightCol')!r} is set but the "
                f"shard store at {store.path} has no weight column "
                "(write_store(..., weight=...))")
        objective, num_class, groups = self._store_fit_spec(store)
        booster = self._train_booster(store, None, None,
                                      np.zeros(1, bool), num_class,
                                      objective=objective, groups=groups)
        return self._make_store_model(booster)

    def fit_param_maps(self, df: DataFrame, maps):
        def sequential():
            return [self.copy(pm)._fit(df) for pm in maps]

        keys = set().union(*[set(m) for m in maps]) if maps else set()
        vmappable = (
            bool(maps) and keys <= set(self._VMAP_PARAM_FIELDS)
            and not self.get("earlyStoppingRound")
            and not self.get("itersPerCall")  # sweep would compile unbounded
            and not self.get("numBatches")
            and self.get("delegate") is None
            and not self.get("modelString")
            and self.get("boostingType") != "dart"  # B x [T, N] delta memory
            and self._supports_vmap_fit()
            and stratlib.normalize_parallelism(
                self.get("parallelism")) != "voting_parallel")
        if not vmappable:
            return sequential()

        def val(pm, name):
            return float(pm.get(name, self.get(name)))

        cols = {field: np.asarray([val(pm, pname) for pm in maps], np.float32)
                for pname, field in self._VMAP_PARAM_FIELDS.items()}
        # booster metadata records the user's learningRate even for rf
        # (training uses 1.0 — rf averages, it does not shrink), matching the
        # sequential path's exported model strings; python floats, not the
        # f32-rounded training values, so model_string() output is identical
        meta_lrs = [val(pm, "learningRate") for pm in maps]
        if self.get("boostingType") == "rf":
            if (cols["bagging_fraction"] >= 1.0).any():
                # per-map rf contract violation: let the sequential path
                # raise the proper per-candidate error
                return sequential()
            cols["learning_rate"] = np.ones(len(maps), np.float32)
        hp_batch = HParams(**{fld: jnp.asarray(cols[fld])
                              for fld in HParams._fields})
        self._hp_batch = hp_batch
        self._hp_meta_lrs = meta_lrs
        # bagging STRUCTURE is static: if any candidate bags, the compiled
        # program must include the bagging mask (prob comes from HParams)
        self._bagging_fraction_static = float(cols["bagging_fraction"].min())
        try:
            model0 = self._fit(df)
            boosters = self._vmap_boosters
        finally:
            self._hp_batch = None
            self._hp_meta_lrs = None
            self._vmap_boosters = None
            self._bagging_fraction_static = None
        models = [model0]
        for booster in boosters[1:]:
            m = copy.copy(model0)
            m._paramMap = dict(model0._paramMap)
            m.booster = booster
            models.append(m)
        return models

    def _make_config(self, num_class: int, axis_name: Optional[str],
                     objective: Optional[str] = None,
                     has_init_score: bool = False) -> GBDTConfig:
        boosting = self.get("boostingType")
        bag_frac = (self._bagging_fraction_static
                    if getattr(self, "_bagging_fraction_static", None)
                    is not None else self.get("baggingFraction"))
        if boosting == "rf" and (self.get("baggingFreq") <= 0
                                 or bag_frac >= 1.0):
            raise ValueError(
                "boostingType='rf' requires baggingFreq > 0 and "
                "baggingFraction < 1.0 (LightGBM random-forest contract)")
        return GBDTConfig(
            num_leaves=self.get("numLeaves"),
            num_iterations=self.get("numIterations"),
            # rf trees are averaged, not shrunk
            learning_rate=1.0 if boosting == "rf" else self.get("learningRate"),
            max_bins=self.get("maxBin"),
            max_depth=self.get("maxDepth"),
            lambda_l1=self.get("lambdaL1"),
            lambda_l2=self.get("lambdaL2"),
            min_data_in_leaf=self.get("minDataInLeaf"),
            min_sum_hessian_in_leaf=self.get("minSumHessianInLeaf"),
            min_gain_to_split=self.get("minGainToSplit"),
            bagging_fraction=bag_frac,
            bagging_freq=self.get("baggingFreq"),
            pos_bagging_fraction=self.get("posBaggingFraction"),
            neg_bagging_fraction=self.get("negBaggingFraction"),
            feature_fraction=self.get("featureFraction"),
            max_delta_step=self.get("maxDeltaStep"),
            boost_from_average=self.get("boostFromAverage"),
            num_class=num_class,
            objective=objective or self._objective_name(),
            alpha=self.get("alpha"),
            tweedie_variance_power=self.get("tweedieVariancePower"),
            top_rate=self.get("topRate"),
            other_rate=self.get("otherRate"),
            drop_rate=self.get("dropRate"),
            skip_drop=self.get("skipDrop"),
            boosting_type=boosting,
            has_init_score=bool(has_init_score),
            seed=self.get("seed"),
            bagging_seed=self.get("baggingSeed"),
            hist_method=getattr(self, "_hist_method_resolved", None)
            or self.get("histMethod"),
            hist_chunk=getattr(self, "_hist_chunk_resolved", None)
            or self.get("histChunk"),
            hist_dtype=self.get("histDtype"),
            split_refresh=self.get("histRefresh"),
            split_scan=self.get("histScan"),
            splits_per_pass=self.get("splitsPerPass"),
            categorical_features=tuple(self._categorical_indexes()),
            missing_features=getattr(self, "_missing_idx", ()),
            cat_smooth=self.get("catSmooth"),
            max_cat_threshold=self.get("maxCatThreshold"),
            axis_name=axis_name,
            # resolved by the comm-model chooser in _train_booster_once
            # ('auto' never reaches the compiled config); the fallback
            # covers direct _make_config callers outside a fit
            tree_learner=(getattr(self, "_tree_learner_resolved", None)
                          or stratlib.choose_strategy(
                              self.get("parallelism"), 1, 1,
                              self.get("maxBin"), self.get("numLeaves"),
                              self.get("topK")).strategy),
            top_k=self.get("topK"),
            eval_metric=self._resolve_metric(
                objective or self._objective_name(), num_class),
        )

    def _categorical_indexes(self):
        """Resolve categorical feature indexes from index/name params
        (LightGBMUtils.getCategoricalIndexes, LightGBMUtils.scala:74-106)."""
        idx = list(self.get("categoricalSlotIndexes") or [])
        names = self.get("categoricalSlotNames")
        slots = self.get("slotNames")
        if names and slots:
            idx += [i for i, s in enumerate(slots) if s in set(names)]
        return sorted(set(int(i) for i in idx))

    def _train_booster(self, x: np.ndarray, y: np.ndarray, w: np.ndarray,
                       is_valid: np.ndarray, num_class: int,
                       objective: Optional[str] = None,
                       init_score: Optional[np.ndarray] = None,
                       groups: Optional[np.ndarray] = None) -> Booster:
        """Full training entry: handles warm start (modelString) and batch
        training (numBatches, LightGBMBase.scala:28-50) by folding previous
        boosters' margins into the next run's init scores, then merging trees."""
        objective = objective or self._objective_name()
        prev: Optional[Booster] = None
        if self.get("modelString"):
            from .native_format import parse_model_string
            prev = parse_model_string(self.get("modelString"))

        # consume the dataset pack: clear the estimator's reference now so a
        # long-lived estimator doesn't pin the binned/feature matrices after
        # the dataset itself is dropped
        pb = getattr(self, "_prebinned", None)
        self._prebinned = None
        num_batches = self.get("numBatches")
        ckdir = self.get("checkpointDir")
        self._ck_store = None
        self._ck_resume_trees = 0
        self._ck_resume_batch = 0
        if ckdir:
            store = CheckpointStore(ckdir,
                                    keep_last=self.get("checkpointKeepLast"))
            self._ck_store = store
            restored = store.restore()
            if restored is None:
                legacy = os.path.join(ckdir, "booster.txt")
                if os.path.exists(legacy):
                    # pre-elastic single-file checkpoint (no manifest, no
                    # digest): accepted once for continuity and superseded
                    # by store snapshots at the first chunk boundary
                    with open(legacy) as fh:
                        restored = (fh.read(), None)
            if restored is not None:
                from .native_format import parse_model_string
                payload, man = restored
                # the checkpoint's tree count includes any modelString
                # warm-start trees save_ck folded in — only the NEW trees
                # of the in-flight batch count against numIterations
                base_trees = (int(jax.tree_util.tree_leaves(
                    prev.trees)[0].shape[0]) if prev is not None else 0)
                ck_prev = parse_model_string(payload)
                ck_trees = int(jax.tree_util.tree_leaves(
                    ck_prev.trees)[0].shape[0])
                # the checkpoint supersedes modelString: it was written by
                # a fit that had already folded modelString into its margins
                prev = ck_prev
                if man is not None:
                    self._ck_resume_batch = int(man.get("batch_index", 0))
                    start_trees = int(man.get("extra", {}).get(
                        "batch_start_trees", base_trees))
                else:
                    start_trees = base_trees
                self._ck_resume_trees = ck_trees - start_trees
                cur_ck = man.get("shard_cursor") if man is not None else None
                if cur_ck is not None and hasattr(x, "manifest_digest"):
                    # shard-cursor resume contract (schema v2): the
                    # snapshot names the exact store it trained on — a
                    # rewritten/substituted store is a counted refusal,
                    # never a silent continuation on wrong data
                    if cur_ck.get("manifest_digest") != x.manifest_digest:
                        from ...resilience.elastic import publish_event
                        publish_event("resume", outcome="store_mismatch")
                        raise ValueError(
                            f"checkpoint at {ckdir} was written against "
                            f"shard store digest "
                            f"{cur_ck.get('manifest_digest', '')[:12]}… "
                            f"but the store at {x.path} has digest "
                            f"{x.manifest_digest[:12]}…; refusing to "
                            "resume on different data (clear the "
                            "checkpointDir to train fresh)")
                if num_batches and num_batches > 1 \
                        and self._ck_resume_trees >= \
                        self.get("numIterations"):
                    # the crash landed in the window between a batch's
                    # final snapshot and the next batch's first one: the
                    # in-flight batch is count-complete, so resume STARTS
                    # at the next batch — its delegate batch hooks must
                    # not re-fire around a no-op train
                    self._ck_resume_batch += 1
                    self._ck_resume_trees = 0
                # elastic-resume telemetry: was the snapshot written at a
                # different device count than this fit resumes at? Booster
                # state is replicated either way; rows re-shard at the
                # current mesh (shard_rows) inside the fit below.
                from ...resilience.elastic import publish_event
                cur = self.get("numTasks") or meshlib.device_count()
                same = man is None or int(man.get("ndev", cur)) == cur
                publish_event("resume",
                              outcome="same_ndev" if same else "reshard")
        if num_batches and num_batches > 1:
            rng = np.random.default_rng(self.get("seed"))
            if groups is not None:
                # split on query-group boundaries so lambdarank pair gradients
                # and IDCG normalization always see whole groups (the reference
                # keeps groups intact via repartitionByGroupingColumn,
                # LightGBMRanker.scala:77+)
                uniq = np.unique(groups)
                gperm = rng.permutation(uniq)
                gparts = np.array_split(gperm, num_batches)
                parts = [np.flatnonzero(np.isin(groups, gp)) for gp in gparts]
            else:
                order = rng.permutation(len(y))
                parts = np.array_split(order, num_batches)
            booster = prev
            delegate = self.get("delegate")
            for bi, part in enumerate(parts):
                if bi < self._ck_resume_batch:
                    # this batch's trees are already inside the restored
                    # snapshot (its margins fold back in through `booster`
                    # below); its delegate batch hooks ran in the crashed
                    # fit and are not replayed
                    continue
                self._batch_index = bi
                if delegate is not None:
                    delegate.before_train_batch(bi, None, booster)
                booster = self._train_booster_once(
                    x[part], y[part], w[part], is_valid[part], num_class,
                    objective,
                    init_score[part] if init_score is not None else None,
                    booster,
                    groups[part] if groups is not None else None,
                    # dataset bins are full-data: slice rows, keep edges
                    prebinned=((pb[0], pb[1][part], pb[2])
                               if pb is not None else None))
                # only the in-flight batch resumes mid-way; later batches
                # train their full numIterations
                self._ck_resume_trees = 0
                if delegate is not None:
                    delegate.after_train_batch(bi, None, booster)
            self._clear_checkpoints()
            return booster
        self._batch_index = 0
        booster = self._train_booster_once(x, y, w, is_valid, num_class,
                                           objective, init_score, prev,
                                           groups, prebinned=pb)
        self._clear_checkpoints()
        return booster

    def _clear_checkpoints(self) -> None:
        """A completed fit's snapshots are crash artifacts: remove them
        (legacy single-file checkpoints included) so the next fit with
        this checkpointDir starts fresh. Never called on the failure
        path — a crash/drain leaves the snapshots for the resume."""
        store = getattr(self, "_ck_store", None)
        if store is None:
            return
        store.clear()
        try:
            os.remove(os.path.join(store.directory, "booster.txt"))
        except OSError:
            pass
        self._iters_override = None

    def _train_booster_once(self, x: np.ndarray, y: np.ndarray, w: np.ndarray,
                            is_valid: np.ndarray, num_class: int,
                            objective: str,
                            init_score: Optional[np.ndarray],
                            prev: Optional[Booster],
                            groups: Optional[np.ndarray] = None,
                            prebinned=None) -> Booster:
        _store = None
        if not isinstance(x, np.ndarray):
            from ...io.shardstore import ShardStore
            if isinstance(x, ShardStore):
                _store = x
        n, f = x.shape  # ShardStore mirrors the 2-D .shape surface
        k = num_class if num_class > 1 else 1
        _sw = None
        if self.get("collectFitTimings"):
            from ...utils.profiling import StopWatch
            _sw = StopWatch()
        _t_fit0 = __import__("time").perf_counter()
        _dlg = self.get("delegate")
        _bi = getattr(self, "_batch_index", 0)
        if _dlg is not None:
            _dlg.before_generate_train_dataset(_bi, self)
        # serial fits at scale take the pipelined dataset path (binning
        # overlapped with the device transfer); under collectFitTimings the
        # sequential path keeps the binning/transfer phases separable, while
        # fitPipeline='on' + collectFitTimings records the barrier-free
        # FitTimeline instead (overlap measured, not inferred).
        # the serial/sharded decision, made ONCE here and reused by the
        # mesh-placement code below (drift between two copies of this
        # predicate would route a committed device array into place_rows).
        # parallelism='auto' (the default) resolves through the comm-model
        # chooser: sharded whenever >1 device is visible, voting_parallel
        # exactly where the closed-form traffic model predicts >= threshold
        # savings over data_parallel (parallel/strategy.py; the dryrun
        # measures 2.04x vs the model's 1.97x at F=512). The decision is
        # published to the telemetry registry and attached to the booster.
        ndev = self.get("numTasks") or meshlib.device_count()
        decision = stratlib.choose_strategy(
            self.get("parallelism"), ndev, f, self.get("maxBin"),
            self.get("numLeaves"), self.get("topK"),
            # a vmapped candidate batch pins data_parallel: per-candidate
            # voting programs would defeat the single compiled batch
            allow_voting=getattr(self, "_hp_batch", None) is None,
            # fleet topology (ISSUE 15): recorded on the decision and
            # priced by the ICI/DCN comm terms; 1 host everywhere except
            # a connected multihost fabric
            hosts=meshlib.process_count(),
            devices_per_host=meshlib.local_device_count())
        par = decision.strategy
        serial = (par == "serial" or ndev <= 1)
        self._tree_learner_resolved = par
        self._strategy_decision = decision
        fp = self.get("fitPipeline")
        if fp not in ("auto", "on", "off"):
            raise ValueError(
                f"fitPipeline must be auto, on or off, got {fp!r}")
        # the grouped (lambdarank) sharded layout reorders rows into
        # group-aligned shards — incompatible with the streaming block
        # buffer, so it keeps the one-shot placement path. A multi-host
        # sharded fit takes the pipelined path at ANY size: its dataset
        # construction is where each host bins only its own rows
        # (multihost.binned_to_device), so routing through it is what
        # makes host binning cost divide by the host count.
        _multihost = (not serial) and meshlib.process_count() > 1
        _pipelined = (prebinned is None and (serial or groups is None)
                      and isinstance(x, np.ndarray) and x.ndim == 2
                      and (fp == "on"
                           or (fp == "auto" and _multihost
                               and groups is None)
                           or (fp == "auto" and _sw is None
                               and x.dtype == np.float32
                               and n >= 2_000_000)))
        self._last_fit_pipelined = bool(_pipelined)

        # margin assembly hoisted ABOVE dataset construction (it only needs
        # raw features): the pipelined path dispatches its device copy
        # before the block loop, hiding the transfer under host binning.
        # A shard-store fit never materializes an [n, k] host margin —
        # warm-start margins stream per block inside the ingest ring.
        margin = None if _store is not None else np.zeros((n, k), np.float32)
        has_init = False
        if init_score is not None:
            margin += init_score.reshape(n, -1).astype(np.float32)
            has_init = True
        if prev is not None:
            if _store is None:
                pm = prev.raw_predict(x)
                margin += pm.reshape(n, -1).astype(np.float32)
            has_init = True

        _tl = None
        _aux = None
        if _store is not None:
            # out-of-core dataset construction (io/shardstore.py): the
            # binned matrix and every aux array stream from disk shards
            # through a bounded prefetch ring — the full feature matrix
            # never exists in host memory, and the streamed arrays are
            # bit-identical to the in-memory route (digest parity,
            # tests/test_shardstore.py)
            if prebinned is not None:
                raise ValueError("LightGBMDataset prebinning does not "
                                 "compose with shard-store input")
            if groups is not None and not serial:
                raise ValueError(
                    "lambdarank from a shard store is serial-only: the "
                    "sharded grouped layout reorders rows into group-"
                    "aligned shards, which defeats streaming ingest — "
                    "set numTasks=1 or parallelism='serial'")
            from ...io import shardstore as sstore
            _tl = FitTimeline() if _sw is not None else NULL_TIMELINE
            with _tl.span("edges_fit"):
                bm = self._fit_bin_mapper_store(x)
            self._missing_idx = self._missing_idx_of(bm)
            margin_fn = None
            if prev is not None:
                margin_fn = (lambda feats: prev.raw_predict(feats)
                             .reshape(feats.shape[0], -1)
                             .astype(np.float32))
            binned, _aux = sstore.stream_fit_arrays(
                bm, x, k=k,
                mesh=None if serial else meshlib.get_mesh(ndev),
                margin_fn=margin_fn, timeline=_tl)
            if groups is not None:
                # serial lambdarank: group ids are small (one int per
                # row) — the layout rides beside the streamed arrays
                from ...ops.ranking import make_group_layout
                _aux = _aux[:4] + (jnp.asarray(
                    make_group_layout(groups).group_idx),)
            if _sw is None:
                _tl = None
            self._last_fit_pipelined = True
        elif _sw is not None and not _pipelined:
            with _sw.measure("binning", barrier=False):
                if prebinned is not None:
                    bm, binned, self._missing_idx = prebinned
                else:
                    bm, binned, self._missing_idx = self._fit_binning(x)
        elif prebinned is not None:  # LightGBMDataset: bins computed once
            bm, binned, self._missing_idx = prebinned
        elif _pipelined:
            _tl = FitTimeline() if _sw is not None else NULL_TIMELINE
            with _tl.span("edges_fit"):
                bm = self._fit_bin_mapper(x)
            self._missing_idx = self._missing_idx_of(bm)
            binned, _aux = self._pipelined_device_data(
                bm, x, y, w, is_valid, margin, has_init, k, groups, _tl,
                mesh=None if serial else meshlib.get_mesh(ndev))
            if _sw is None:
                _tl = None
        else:
            bm, binned, self._missing_idx = self._fit_binning(x)
        if _dlg is not None:
            _dlg.after_generate_train_dataset(_bi, self)

        if self.get("histDtype") not in ("bf16", "f32"):
            raise ValueError(
                f"histDtype must be bf16 or f32, got {self.get('histDtype')!r}")
        if self.get("histRefresh") not in ("eager", "lazy"):
            raise ValueError(
                f"histRefresh must be eager or lazy, got "
                f"{self.get('histRefresh')!r}")
        if self.get("histScan") not in ("full", "compact"):
            raise ValueError(
                f"histScan must be full or compact, got "
                f"{self.get('histScan')!r}")
        if self.get("histScan") == "compact":
            if self.get("histRefresh") == "lazy":
                raise ValueError(
                    "histScan='compact' requires histRefresh='eager' (lazy "
                    "has no per-split pass to compact)")
            if par == "voting_parallel":
                raise ValueError(
                    "histScan='compact' does not compose with "
                    "parallelism='voting_parallel' (voting needs full local "
                    "histograms per slot; with parallelism='auto' the comm "
                    "model chose voting at this shape — set "
                    "parallelism='data' to keep compact)")
        if self.get("splitsPerPass") > 1:
            if (self.get("histRefresh") == "lazy"
                    or self.get("histScan") == "compact"):
                raise ValueError(
                    "splitsPerPass > 1 is the batched variant of the "
                    "eager/full scan; it does not compose with "
                    "histRefresh='lazy' or histScan='compact'")
        if ((self.get("posBaggingFraction") >= 0
             or self.get("negBaggingFraction") >= 0)
                and (objective or self._objective_name()) != "binary"):
            raise ValueError(
                "posBaggingFraction/negBaggingFraction can only be used with "
                "the binary objective (upstream LightGBM restriction)")
        if self.get("histMethod") == "autotune":
            # measured kernel selection at the problem's actual shape
            # (ops/autotune.py); resolved once per fit, cached per backend
            from ...ops.autotune import pick_hist_config
            m, c = pick_hist_config(n, f, self.get("maxBin"),
                                    self.get("numLeaves"),
                                    dtype=self.get("histDtype"))
            self._hist_method_resolved, self._hist_chunk_resolved = m, c

        # par arrives pre-validated: choose_strategy normalizes the param
        # (unknown values raise there, naming the accepted surface)
        if par == "voting_parallel" and self.get("topK") < 1:
            raise ValueError("topK must be >= 1 for voting_parallel")
        key = jax.random.PRNGKey(self.get("seed"))
        is_train = (~is_valid).astype(np.float32)
        axis = meshlib.DATA_AXIS
        gidx = None

        if serial:
            cfg = self._make_config(num_class, None, objective, has_init)
            if _aux is not None:
                # pipelined construction: every array was dispatched async
                # during/ahead of the block loop — no fresh transfers here
                y_d, w_d, t_d, mg_d, gidx = _aux
                data = (binned, y_d, w_d, t_d, mg_d)
            else:
                if groups is not None:
                    from ...ops.ranking import make_group_layout
                    gidx = jnp.asarray(make_group_layout(groups).group_idx)
                data = (jnp.asarray(binned), jnp.asarray(y), jnp.asarray(w),
                        jnp.asarray(is_train), jnp.asarray(margin))
            jfull, jchunk = _compiled_serial(cfg)

            def _st_kw(st):
                # optional dart carry (deltas, tree_scale) -> chunk kwargs
                return ({} if st is None
                        else {"deltas_in": st[0], "tree_scale_in": st[1]})
            if gidx is None:
                run_full = lambda k: jfull(*data, k)
                run_chunk = (lambda k, s, sc, lr, st=None:
                             jchunk(*data, k, s, sc, lr, **_st_kw(st)))
            else:
                run_full = lambda k: jfull(*data, k, gidx)
                run_chunk = (lambda k, s, sc, lr, st=None:
                             jchunk(*data, k, s, sc, lr, gidx,
                                    **_st_kw(st)))
            n_rows_exec = binned.shape[0]
        else:
            cfg = self._make_config(num_class, axis, objective, has_init)
            m = meshlib.get_mesh(ndev)
            nd = m.shape[axis]
            # replicated small state (PRNG key) keeps place_global — the
            # device_put lint's allowlist; ROW data must go through
            # shard_rows/place_rows below
            key = meshlib.place_global(m, key, P())
        if not serial and groups is not None:
            # group-aligned sharding: whole query groups per device
            # (repartitionByGroupingColumn equivalent, LightGBMRanker.scala:77+)
            from ...ops.ranking import make_sharded_group_layout
            lay = make_sharded_group_layout(groups, nd)

            def take_pad(arr, fill=0.0):
                out = np.zeros((lay.order.shape[0],) + arr.shape[1:], arr.dtype)
                ok = lay.order >= 0
                out[ok] = arr[lay.order[ok]]
                return out

            place = lambda a: meshlib.place_rows(m, a)
            gidx = place(lay.group_idx)
            w_pad = take_pad(w)  # padding rows (order == -1) get weight 0
            data = (place(take_pad(binned)),
                    place(take_pad(np.asarray(y, np.float64))),
                    place(w_pad), place(take_pad(is_train)),
                    place(take_pad(margin)))
            jfull, jchunk = _compiled_sharded(cfg, ndev, True)
            run_full = lambda k: jfull(*data, k, gidx)
            run_chunk = (lambda k, s, sc, lr, st=None:
                         jchunk(*data, k, s, sc, lr, *(st or ()), gidx))
            n_rows_exec = lay.order.shape[0]
        elif not serial:
            if _aux is not None:
                # pipelined sharded construction: the binned matrix
                # streamed through per-shard double-buffered blocks and
                # every aux array was dispatched async under the block
                # loop (already padded, row-sharded, zero-weight-folded)
                y_d, w_d, t_d, mg_d, _gu = _aux
                data = (binned, y_d, w_d, t_d, mg_d)
            else:
                # the canonical sharded layout: shard_rows pads the row
                # dimension to the data axis, places with NamedSharding,
                # and folds caller weights with the padding mask so a
                # padded row can never carry weight into a histogram
                b_p, y_p, t_p, m_p, w_p, _mask = meshlib.shard_rows(
                    m, binned, np.asarray(y, np.float64), is_train, margin,
                    weights=w)
                data = (b_p, y_p, w_p, t_p, m_p)
            jfull, jchunk = _compiled_sharded(cfg, ndev, False)
            run_full = lambda k: jfull(*data, k)
            run_chunk = (lambda k, s, sc, lr, st=None:
                         jchunk(*data, k, s, sc, lr, *(st or ())))
            n_rows_exec = data[0].shape[0]

        rounds = self.get("earlyStoppingRound")
        delegate = self.get("delegate")
        has_valid = bool(is_valid.any())
        ipc = self.get("itersPerCall")
        ckdir = self.get("checkpointDir")
        if ckdir and self.get("boostingType") == "dart":
            raise ValueError(
                "checkpointDir is not supported with boostingType='dart': "
                "resuming dropout needs the per-iteration delta history "
                "([T,N,K] device state) — training state the snapshot "
                "manifest does not carry (it would take a schema_version-2 "
                "manifest recording the delta/rescale arrays beside "
                "'step', resilience/elastic.SCHEMA_VERSION). itersPerCall "
                "DOES compose with dart (the delta history is carried "
                "on-device across chunks)")
        if rounds and has_valid and self.get("boostingType") == "dart":
            raise ValueError(
                "earlyStoppingRound is not supported with "
                "boostingType='dart' (matching upstream LightGBM: dropped-"
                "tree rescaling makes a truncated-at-best-iteration model "
                "inconsistent, and the halt needs chunked training)")
        # _iters_override feeds ONLY _run_chunked's trip count (the resume
        # path is always chunked); cfg.num_iterations stays the full value
        # and run_full is never used with a checkpointDir, so no compiled
        # program depends on the override
        self._iters_override = None
        if ckdir:
            resume_trees = getattr(self, "_ck_resume_trees", 0)
            remaining = self.get("numIterations") - resume_trees
            if remaining <= 0:
                # the crashed fit had already snapshotted every requested
                # iteration of this batch: deliver it (the crash artifacts
                # are cleared by _train_booster once the WHOLE fit — all
                # batches — completes)
                return prev
            if resume_trees:
                self._iters_override = remaining
        use_chunked = (delegate is not None or (rounds and has_valid)
                       or bool(ipc) or bool(ckdir))

        hp_batch = getattr(self, "_hp_batch", None)
        if hp_batch is not None and ckdir:
            raise ValueError(
                "checkpointDir is not supported with fit(df, paramMaps) "
                "(candidates would race on one checkpoint file)")
        if hp_batch is not None:
            # vmapped multi-candidate training (fit(df, paramMaps)): one
            # compiled program trains every HParams candidate; per-candidate
            # boosters are stashed for fit_param_maps, the first is returned
            # so the subclass _fit completes normally
            nb = len(jax.tree.leaves(hp_batch)[0])
            grouped = gidx is not None
            vfull = (_compiled_serial_vmapped(cfg, grouped) if serial
                     else _compiled_sharded_vmapped(cfg, ndev, grouped))
            keys = jnp.tile(key[None], (nb,) + (1,) * key.ndim)
            args = (*data, keys, hp_batch) + ((gidx,) if grouped else ())
            res_b = jax.tree.map(np.asarray, vfull(*args))
            lrs = getattr(self, "_hp_meta_lrs", None)
            self._vmap_boosters = []
            for i in range(nb):
                res_i = jax.tree.map(lambda a: a[i], res_b)
                self._vmap_boosters.append(self._assemble_booster(
                    res_i, bm, num_class, objective, f,
                    self._select_best_iteration(res_i, has_valid), prev,
                    learning_rate=(float(lrs[i]) if lrs is not None
                                   else None)))
            return self._vmap_boosters[0]

        save_ck = None
        if ckdir:
            ck_store = self._ck_store
            ck_ndev = 1 if serial else ndev
            # trees in the booster when THIS batch began (warm start +
            # completed batches; on a resume, `prev` additionally carries
            # the in-flight batch's partial trees — subtract them): the
            # manifest field a mid-batch resume subtracts from the
            # snapshot's total to find the in-flight batch's progress
            _batch_start_trees = (int(jax.tree_util.tree_leaves(
                prev.trees)[0].shape[0]) if prev is not None else 0) \
                - getattr(self, "_ck_resume_trees", 0)

            def save_ck(partial: BoostResult) -> None:
                """Durable booster-so-far snapshot at a chunk boundary:
                atomic payload + digest manifest, keep-last-K retention
                (resilience/elastic.CheckpointStore). Multi-host fits
                write from process 0 only: booster state is replicated,
                so every host would write byte-identical snapshots — on a
                SHARED checkpointDir (the resumable-pod contract,
                docs/MULTIHOST.md) concurrent writers would race the
                sequence numbering for no added durability."""
                if meshlib.process_count() > 1 and jax.process_index() != 0:
                    return
                bst = self._assemble_booster(partial, bm, num_class,
                                             objective, f, None, prev)
                ck_store.save(
                    bst.model_string(),
                    step=int(jax.tree_util.tree_leaves(
                        bst.trees)[0].shape[0]),
                    ndev=ck_ndev,
                    batch_index=getattr(self, "_batch_index", 0),
                    extra={"batch_start_trees": _batch_start_trees},
                    shard_cursor=(x.cursor() if _store is not None
                                  else None))

        _chunk_tl = None
        _straggler_gap_s = None
        if _sw is not None and not serial:
            # per-shard straggler gap (arxiv 1612.01437: straggler
            # structure, not FLOPs, dominates distributed wall): POLL
            # every addressable shard of the binned matrix for readiness
            # and stamp each shard's first-ready time — max-min is how
            # long the slowest device's transfer trailed the fastest,
            # resolved to the poll interval. Polling (is_ready) instead
            # of sequential block_until_ready: blocking shard 0 first
            # would hide any straggler that finished while we waited on
            # it (visit-order bias). Timings mode only (this waits out
            # every transfer); published as a registry gauge.
            import time as _tm
            shards = [s.data for s in data[0].addressable_shards]
            first_ready = [None] * len(shards)
            if shards and hasattr(shards[0], "is_ready"):
                while any(t is None for t in first_ready):
                    now = _tm.perf_counter()
                    for i, sd in enumerate(shards):
                        if first_ready[i] is None and sd.is_ready():
                            first_ready[i] = now
                    _tm.sleep(2e-4)
            else:  # very old jax: fall back to the order-biased bound
                for i, sd in enumerate(shards):
                    jax.block_until_ready(sd)
                    first_ready[i] = _tm.perf_counter()
            _straggler_gap_s = ((max(first_ready) - min(first_ready))
                                if first_ready else 0.0)
        if _sw is not None:
            import time as _tm
            if _tl is not None:
                # pipelined timeline mode: the DESIGNATED commit barrier —
                # the one host sync of the construction stage, at
                # first-dispatch time. Its measured wait is the transfer
                # backlog NOT hidden under host binning.
                with _tl.span("commit_wait", kind="wait"):
                    jax.block_until_ready(data)
                # calibrate the total transfer backlog (the 'device' stream
                # of the overlap ratio): one block's d2h round trip
                # approximates one block's h2d cost over the same link,
                # scaled by the block count. An estimate, flagged as such
                # in the timeline — measuring h2d per block exactly would
                # need the per-block barriers this pipeline removes.
                nb = int(_tl.meta.get("n_blocks", 1))
                cb = int(_tl.meta.get("blk", n))
                if meshlib.process_count() == 1:
                    # multi-host: a leading slice of the GLOBAL row-sharded
                    # array spans non-addressable devices — fetching it
                    # raises; the estimate is skipped rather than crashing
                    # an instrumented fabric fit
                    _t0 = _tm.perf_counter()
                    np.asarray(binned[:cb])
                    _tl.add_span("transfer_estimate", "device",
                                 (_tm.perf_counter() - _t0) * nb)
                _sw._acc["construction"] = {"total_s": _tl.wall_s,
                                            "count": 1.0}
                if use_chunked:
                    _chunk_tl = FitTimeline()
            else:
                _t0 = _tm.perf_counter()
                jax.block_until_ready(data)
                _sw._acc["device_transfer"] = {
                    "total_s": _tm.perf_counter() - _t0, "count": 1.0}

        def _boost():
            if use_chunked:
                # preemption drain: SIGTERM/SIGINT handlers live exactly as
                # long as the chunk loop can act on them — the loop checks
                # drain.requested at every chunk boundary, finishes the
                # in-flight chunk, snapshots, and raises Preempted inside
                # the grace budget
                drain_cm = (PreemptionDrain(grace_s=self.get("drainGraceS"))
                            if save_ck is not None
                            else contextlib.nullcontext(None))
                with drain_cm as drain:
                    self._drain = drain
                    try:
                        return self._run_chunked(
                            run_chunk, key, n_rows_exec, k, rounds,
                            has_valid, delegate, save_ck=save_ck,
                            timeline=_chunk_tl,
                            mesh=None if serial else m)
                    finally:
                        self._drain = None
            res = jax.tree.map(np.asarray, run_full(key))
            return res, self._select_best_iteration(res, has_valid)

        if _sw is not None:
            # np.asarray fetches are synchronous — no barrier needed
            with _sw.measure("boosting", barrier=False):
                result, best_iter = _boost()
            with _sw.measure("assemble", barrier=False):
                booster = self._assemble_booster(result, bm, num_class,
                                                 objective, f, best_iter,
                                                 prev)
            timings = _sw.summary()
            timings["total"] = {
                "total_s": (__import__("time").perf_counter() - _t_fit0),
                "count": 1.0}
            if _tl is not None:
                timings["timeline"] = {"construction": _tl.summary()}
                if _chunk_tl is not None:
                    timings["timeline"]["chunks"] = _chunk_tl.summary()
            booster.fit_timings = timings
        else:
            result, best_iter = _boost()
            booster = self._assemble_booster(result, bm, num_class,
                                             objective, f, best_iter, prev)
        # observability bridge (fit-loop hook): every completed fit lands
        # its headline throughput in the telemetry registry; a
        # collectFitTimings fit additionally lands the phase decomposition
        # and pipelined-construction timeline, so one /metrics scrape (or
        # the bench snapshot) carries fit-side and serving-side telemetry.
        # Import inside the guard: telemetry must never fail a fit. The
        # iteration count is the EXECUTED one (_iters_override on a
        # checkpoint resume), not the nominal request — the wall time
        # only covers this run, and rows*iter/s must not inflate on
        # resume.
        booster.fit_strategy = decision._asdict()
        if _straggler_gap_s is not None and _sw is not None:
            timings["shard_straggler_gap_s"] = {
                "total_s": _straggler_gap_s, "count": 1.0}
        try:
            from ...observability import (publish_fit_metrics,
                                          publish_multichip_fit)
            publish_fit_metrics(
                n, self._iters_override or self.get("numIterations"),
                __import__("time").perf_counter() - _t_fit0,
                timings=getattr(booster, "fit_timings", None))
            publish_multichip_fit(decision,
                                  straggler_gap_s=_straggler_gap_s)
        except Exception:  # noqa: BLE001 - telemetry never fails a fit
            pass
        # checkpoint snapshots are NOT cleared here: numBatches>1 calls
        # this once per batch, and only the whole fit's completion makes
        # them safe to drop (_train_booster._clear_checkpoints)
        return booster

    def _assemble_booster(self, result: BoostResult, bm, num_class: int,
                          objective: str, f: int, best_iter, prev,
                          learning_rate: Optional[float] = None) -> Booster:
        trees = result.trees
        thresholds = self._thresholds_for(trees, bm)
        booster = Booster(trees, thresholds, result.init_score
                          if num_class > 1 else np.float32(result.init_score),
                          objective, num_class, f, bm,
                          self.get("slotNames"), best_iter,
                          (self.get("learningRate") if learning_rate is None
                           else learning_rate),
                          average_output=(self.get("boostingType") == "rf"))
        if prev is not None:
            booster = concat_boosters(prev, booster)
        # per-iteration eval record (trainCore's eval tracking,
        # TrainUtils.scala:258-308) — surfaced as model.train_metrics /
        # valid_metrics; attached AFTER concat (which builds a fresh Booster)
        # and appended to the previous batches' record for batch/warm-start
        # training
        tm = np.asarray(result.train_metric)
        vm = np.asarray(result.valid_metric)
        prev_tm = getattr(prev, "train_metric", None)
        prev_vm = getattr(prev, "valid_metric", None)
        booster.train_metric = (np.concatenate([prev_tm, tm])
                                if prev_tm is not None else tm)
        booster.valid_metric = (np.concatenate([prev_vm, vm])
                                if prev_vm is not None else vm)
        return booster

    def _run_chunked(self, run_chunk, key, n_rows: int, k: int, rounds: int,
                     has_valid: bool, delegate, save_ck=None,
                     timeline=None, mesh=None
                     ) -> Tuple[BoostResult, Optional[int]]:
        """Host-driven chunked boosting: compiled chunks of iterations with a
        stop-check + delegate hooks between chunks.

        This is the jit analogue of the reference's `trainCore` loop actually
        HALTING on early stopping (TrainUtils.scala:220-315): once the
        validation metric stalls for `rounds` iterations no further chunks
        launch, so earlyStoppingRound=10 hit at iteration 50 of 500 costs ~60
        iterations of compute, not 500. Only raw scores carry between chunks;
        chunk sizes are fixed so at most two programs compile (full + final
        partial chunk).

        AHEAD-DISPATCH (the host/device fit pipeline's chunk stage): when no
        host decision can depend on a chunk's results — no delegate (hooks
        and lr schedules read per-iteration metrics) and no active early
        stopping (the stop decision gates the next launch) — chunk i+1 is
        dispatched BEFORE chunk i's host work. Raw scores, the PRNG key and
        dart's dropout state flow device-to-device between calls (they are
        never fetched), so the chunk boundary costs no sync and no relay
        RTT, and all host bookkeeping — metric/tree fetches, accumulation,
        checkpoint serialization — runs in `_fetch_chunk_host` UNDER chunk
        i+1's device execution. Trip count and inputs are identical either
        way, so ahead-dispatch is bit-identical to the sequential loop
        (regression-pinned, tests/test_fit_pipeline.py)."""
        T = (getattr(self, "_iters_override", None)
             or self.get("numIterations"))
        ipc = self.get("itersPerCall")
        chunk = max(1, min(int(rounds) if rounds else 10, T))
        if ipc:
            # explicit device-call bound wins; early stopping still checks
            # between chunks (a larger chunk only delays the halt)
            chunk = max(1, min(int(ipc), T))
        batch_index = getattr(self, "_batch_index", 0)
        # Delegate hooks and lr schedules see ABSOLUTE iteration indices: a
        # checkpointDir resume trains `remaining` iterations (T, done start
        # at 0 — the device-side `start` must stay 0-based to select the
        # margin-init scores), but a delegate-driven schedule must continue
        # from the resumed tree count, not replay from iteration 0.
        it0 = (getattr(self, "_ck_resume_trees", 0)
               if self.get("checkpointDir") else 0)
        base_lr = (1.0 if self.get("boostingType") == "rf"
                   else self.get("learningRate"))
        cur_lr = base_lr
        # the carried raw-score (and dart delta) state is ROW data: on a
        # multi-host mesh the initial zeros must be a global row-sharded
        # array assembled from per-device shards — a single-controller
        # jnp.zeros is not a valid input to a cross-process shard_map
        # program (multihost.zeros_row_sharded; device-side fill, no
        # host transfer either way)
        _mh = mesh is not None and meshlib.process_count() > 1
        scores = (mhlib.zeros_row_sharded(mesh, (n_rows, k)) if _mh
                  else jnp.zeros((n_rows, k), jnp.float32))
        dart = self.get("boostingType") == "dart"
        # dart's dropout state rides ON DEVICE between chunks: per-iteration
        # score deltas [T, N, K] + cumulative rescales [T], returned by one
        # chunk and fed to the next (never fetched to host)
        # replicated small inputs (chunk start, per-iteration lr scale,
        # dart rescales) take place_global on a multi-host mesh for the
        # same reason: every process holds the identical host value, and
        # the global program needs it as ONE replicated jax.Array
        _repl = ((lambda v: meshlib.place_global(mesh, v, P())) if _mh
                 else (lambda v: v))
        dart_state = (((mhlib.zeros_row_sharded(mesh, (T, n_rows, k),
                                                row_axis=1) if _mh
                        else jnp.zeros((T, n_rows, k), jnp.float32)),
                       _repl(jnp.ones((T,), jnp.float32)))
                      if dart else None)
        # running concatenation (not a list of chunks): the checkpoint
        # snapshot and the final result share ONE accumulated copy, so a
        # per-chunk snapshot costs one concat of the so-far model instead
        # of re-concatenating every chunk each time
        trees_acc, tm_acc, vm_acc = None, None, None
        done, best, best_at, stopped = 0, np.inf, 0, False
        init_out = None
        tol = self.get("improvementTolerance")
        tl = timeline if timeline is not None else NULL_TIMELINE
        ahead = delegate is None and not (rounds and has_valid)
        drain = getattr(self, "_drain", None)
        # fit-level chaos hook (resilience.chaos.TrainingFaultInjector):
        # fired per fetched chunk AFTER its snapshot landed — a seeded
        # InjectedKill here is exactly a pool preemption's timing
        boundary_hook = getattr(self, "_chunk_boundary_hook", None)
        fetched_chunks = 0

        def _cat(a, b):
            return np.concatenate([a, b], axis=0)

        def _fetch_chunk_host(trees_c, tm_c, vm_c, init_ref, c, start):
            """The DESIGNATED host fetch + bookkeeping point (the only
            place in the chunk loop allowed to sync on device results —
            sync-point lint, tests/test_fit_pipeline.py). Blocks until
            chunk [start, start+c) completes, then accumulates trees and
            metrics, runs the early-stop comparator and delegate
            after-hooks, and writes the checkpoint snapshot. Under
            ahead-dispatch this whole body executes while the NEXT chunk
            runs on the device."""
            nonlocal trees_acc, tm_acc, vm_acc, best, best_at, stopped, \
                init_out, fetched_chunks
            with tl.span(f"fetch_wait[{start}]", kind="wait"):
                tm_h, vm_h = np.asarray(tm_c), np.asarray(vm_c)
            with tl.span(f"bookkeep[{start}]"):
                trees_h = jax.tree.map(np.asarray, trees_c)
                init_out = np.asarray(init_ref)
                if trees_acc is None:
                    trees_acc, tm_acc, vm_acc = trees_h, tm_h, vm_h
                else:
                    trees_acc = jax.tree.map(_cat, trees_acc, trees_h)
                    tm_acc = np.concatenate([tm_acc, tm_h])
                    vm_acc = np.concatenate([vm_acc, vm_h])
                for j in range(c):
                    i = start + j
                    if rounds and has_valid and not stopped:
                        v = vm_h[j]
                        # reference comparator (TrainUtils.scala:287-298):
                        # lower-is-better improves when score - best < tol
                        if best == np.inf or v - best < tol:
                            best, best_at = v, i
                        elif i - best_at >= rounds:
                            stopped = True
                    if delegate is not None:
                        delegate.after_train_iteration(
                            batch_index, it0 + i, has_valid,
                            stopped or i == T - 1,
                            {"train": float(tm_h[j])},
                            {"valid": float(vm_h[j])} if has_valid else None)
                    if stopped:
                        # is_finished fires exactly once: post-stop
                        # iterations of this chunk were computed but are
                        # dead (truncated below)
                        break
                if save_ck is not None:
                    save_ck(BoostResult(trees_acc, init_out, tm_acc, vm_acc))
            if boundary_hook is not None:
                # after the snapshot write: a kill injected here loses no
                # durable state (the chaos contract under test)
                idx = fetched_chunks
                fetched_chunks += 1
                boundary_hook(idx, start)

        def _finalize_chunks():
            """Designated end-of-training sync (dart's carried rescale
            state is device-resident until every chunk has landed)."""
            nonlocal trees_acc
            if dart:
                # bake the FINAL cumulative rescales into the accumulated
                # trees (the full scan does this after its lax.scan;
                # chunked trees came back raw because later chunks
                # retroactively rescale earlier iterations)
                ts = np.asarray(dart_state[1])[:tm_acc.shape[0]]
                scale = ts.reshape(ts.shape + (1,)
                                   * (trees_acc.leaf_value.ndim - 1))
                trees_acc = trees_acc._replace(
                    leaf_value=trees_acc.leaf_value * scale)
            return BoostResult(trees_acc, init_out, tm_acc, vm_acc)

        pending = None
        while done < T and not stopped:
            if drain is not None and drain.requested:
                break  # preemption drain: the in-flight chunk (pending)
                # is flushed + snapshotted below, then Preempted raised
            c = min(chunk, T - done)
            lrs = []
            for i in range(done, done + c):
                if delegate is not None:
                    delegate.before_train_iteration(batch_index, it0 + i,
                                                    has_valid)
                    cur_lr = float(delegate.get_learning_rate(
                        batch_index, it0 + i, cur_lr))
                lrs.append(cur_lr / base_lr if base_lr else 1.0)
            # the PRNG key carries ACROSS chunks (chunk 1 gets the fit key,
            # chunk i+1 gets chunk i's carried key) — chunked training is
            # bit-identical to the one-program scan for every stochastic
            # mode, dart dropout included
            with tl.span(f"dispatch[{done}]"):
                out = run_chunk(key, _repl(jnp.int32(done)), scores,
                                _repl(jnp.asarray(lrs, jnp.float32)),
                                dart_state)
            if dart:
                (trees_c, tm_c, vm_c, scores, key, d_deltas, d_scale,
                 init_ref) = out
                dart_state = (d_deltas, d_scale)
            else:
                trees_c, tm_c, vm_c, scores, key, init_ref = out
            this = (trees_c, tm_c, vm_c, init_ref, c, done)
            done += c
            if ahead and done < T:
                # chunk i+1's inputs are chunk i's OUTPUT device arrays —
                # available as async values immediately, so the next
                # dispatch happens before this chunk's results are read
                if pending is not None:
                    _fetch_chunk_host(*pending)
                pending = this
            else:
                if pending is not None:
                    _fetch_chunk_host(*pending)
                    pending = None
                _fetch_chunk_host(*this)
        if pending is not None:
            _fetch_chunk_host(*pending)
        if drain is not None and drain.requested and done < T and not stopped:
            # the drained chunk's snapshot is durable: disarm the grace
            # watchdog and surface the clean-exit contract
            drain.completed()
            raise Preempted(
                f"fit drained after preemption signal: {done}/{T} "
                f"iterations snapshotted to checkpointDir — re-run fit() "
                f"with the same checkpointDir (at any device count) to "
                f"resume")
        result = _finalize_chunks()
        best_iter = (best_at + 1) if (rounds and has_valid) else None
        return result, best_iter

    def _select_best_iteration(self, result: BoostResult,
                               has_valid: bool) -> Optional[int]:
        rounds = self.get("earlyStoppingRound")
        if not rounds or not has_valid:
            return None
        vm = np.asarray(result.valid_metric)
        # reference semantics (TrainUtils.scala:258-308): stop once the validation
        # metric hasn't improved for `rounds` iterations, keeping the best iteration.
        # Training runs the full scan here, so find the first stall point and
        # truncate to the best iteration seen before it.
        tol = self.get("improvementTolerance")
        best, best_at = np.inf, 0
        for i, v in enumerate(vm):
            if best == np.inf or v - best < tol:
                best, best_at = v, i
            elif i - best_at >= rounds:
                break
        return best_at + 1

    @staticmethod
    def _thresholds_for(trees: Tree, bm: BinMapper) -> np.ndarray:
        """Real-valued thresholds from bin ids for raw-feature prediction/export."""
        feats = np.asarray(trees.split_feat)
        bins = np.asarray(trees.split_bin)
        edges = bm.edges  # [F, B-1]
        # missing-capable features reserve bin 0: value bin b <-> edge b-1
        bins = bins - bm.missing[feats].astype(bins.dtype)
        b_idx = np.clip(bins, 0, edges.shape[1] - 1)
        thr = edges[feats, b_idx]
        # replace inf padding edges by the feature's largest finite edge
        if not np.isfinite(thr).all():
            finite_max = np.where(np.isfinite(edges), edges, -np.inf).max(axis=1)
            thr = np.where(np.isfinite(thr), thr, finite_max[feats])
        return thr.astype(np.float64)


class LightGBMModelBase(Model, _p.HasFeaturesCol, _p.HasPredictionCol):
    """Shared fitted-model surface (LightGBMModelMethods.scala:1-66)."""

    leafPredictionCol = _p.Param(
        "leafPredictionCol",
        "output column for per-tree leaf indices (empty = off)", "")
    featuresShapCol = _p.Param(
        "featuresShapCol",
        "output column for SHAP contributions (empty = off)", "")

    def __init__(self, booster: Optional[Booster] = None, **kw):
        super().__init__(**kw)
        self.booster = booster

    @property
    def train_metrics(self) -> Optional[np.ndarray]:
        """Per-iteration training metric (metric param or objective default);
        the eval record of TrainUtils.scala:258-308."""
        return getattr(self.booster, "train_metric", None)

    @property
    def valid_metrics(self) -> Optional[np.ndarray]:
        """Per-iteration validation metric (NaN when no validation rows)."""
        return getattr(self.booster, "valid_metric", None)

    def _add_optional_cols(self, df: DataFrame, x: np.ndarray) -> DataFrame:
        """Leaf-index / SHAP output columns (LightGBMClassifier.scala:100-142
        leaf + SHAP UDFs — batched here instead of per-row JNI)."""
        leaf_col = self.get("leafPredictionCol")
        if leaf_col:
            df = df.with_column(leaf_col,
                                self.booster.predict_leaf(x).astype(np.float64))
        shap_col = self.get("featuresShapCol")
        if shap_col:
            df = df.with_column(shap_col, self.booster.features_shap(x))
        return df

    def get_feature_importances(self, importance_type: str = "split"):
        return self.booster.feature_importances(importance_type)

    getFeatureImportances = get_feature_importances

    def get_feature_shaps(self, x: np.ndarray) -> np.ndarray:
        return self.booster.features_shap(np.atleast_2d(np.asarray(x)))

    getFeatureShaps = get_feature_shaps

    def save_native_model(self, path: str) -> None:
        self.booster.save_native_model(path)

    saveNativeModel = save_native_model

    def predict_leaf(self, x: np.ndarray) -> np.ndarray:
        return self.booster.predict_leaf(x)

    # ------------------------------------------------------------ save/load
    def _save_extra(self, path: str):
        import os
        meta = self.booster.to_dict()
        np.savez(os.path.join(path, "booster.npz"), **self.booster.save_arrays())
        return {"booster": meta}

    def _load_extra(self, path: str, extra):
        import os
        arrays = np.load(os.path.join(path, "booster.npz"), allow_pickle=False)
        self.booster = Booster.from_parts(extra["booster"], dict(arrays))
