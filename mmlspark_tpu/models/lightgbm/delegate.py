"""Training delegate hooks — user callbacks into the boosting loop.

Reference: `trait LightGBMDelegate` (lightgbm/LightGBMDelegate.scala:1-60) with
hook sites in TrainUtils.scala:192-218 (before/after iteration, dynamic
learning rate) and LightGBMBase.scala:52-68 (before/after batch).

TPU-native adaptation: the boosting loop is a jit-compiled `lax.scan`, so
per-iteration Python callbacks cannot run *inside* the compiled program.
Training instead proceeds in compiled CHUNKS of iterations
(`make_train_fn(cfg).chunk`); hooks run on the host between chunks:

- `get_learning_rate` / `before_train_iteration` are called for every
  iteration of the upcoming chunk BEFORE it launches (learning rates become a
  per-iteration multiplier array fed to the compiled program);
- `after_train_iteration` is called for every finished iteration right after
  its chunk returns, with the recorded train/valid metric values — the same
  information the reference delivers (TrainUtils.scala:205-212), delayed by at
  most one chunk;
- dataset-generation hooks (`before/after_generate_train_dataset`) fire around
  host-side binning.
"""

from __future__ import annotations

from typing import Optional


class LightGBMDelegate:
    """Subclass and override any hook (all are no-ops by default)."""

    # ------------------------------------------------------------- batches
    def before_train_batch(self, batch_index: int, df, previous_booster
                           ) -> None:
        """LightGBMDelegate.scala beforeTrainBatch."""

    def after_train_batch(self, batch_index: int, df, booster) -> None:
        """LightGBMDelegate.scala afterTrainBatch."""

    # ------------------------------------------------------------ datasets
    def before_generate_train_dataset(self, batch_index: int, params) -> None:
        """Called before host-side binning (beforeGenerateTrainDataset)."""

    def after_generate_train_dataset(self, batch_index: int, params) -> None:
        """Called after host-side binning (afterGenerateTrainDataset)."""

    # ---------------------------------------------------------- iterations
    def before_train_iteration(self, batch_index: int, cur_iter: int,
                               has_valid: bool) -> None:
        """Called before iteration `cur_iter` launches (beforeTrainIteration).
        Runs when the chunk containing the iteration is about to launch."""

    def after_train_iteration(self, batch_index: int, cur_iter: int,
                              has_valid: bool, is_finished: bool,
                              train_eval: Optional[dict],
                              valid_eval: Optional[dict]) -> None:
        """Called after iteration `cur_iter` with its recorded metrics
        (afterTrainIteration). `is_finished` is True on the final iteration —
        by early stop or iteration-count exhaustion."""

    def get_learning_rate(self, batch_index: int, cur_iter: int,
                          previous_learning_rate: float) -> float:
        """Return the learning rate for `cur_iter` (getLearningRate,
        TrainUtils.scala:213-218). Default: keep the previous rate."""
        return previous_learning_rate
