"""LightGBMRanker — lambdarank GBDT over query groups.

Reference analogue: `LightGBMRanker(Model)` (lightgbm/LightGBMRanker.scala:24-162):
objective=lambdarank, `groupCol`, `maxPosition`, `labelGain`, `evalAt`; group-sorted
partitions via `repartitionByGroupingColumn`/`preprocessData`. Here the pairwise lambda
gradients run as batched [G, G] ops inside the jit boosting program (ops/ranking.py) and
group alignment is handled by the sharded group layout rather than a repartition.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.dataframe import DataFrame, dense_matrix
from ...core import params as _p
from .base import LightGBMModelBase, LightGBMParamsBase

Param = _p.Param


class LightGBMRanker(LightGBMParamsBase):
    """Learning-to-rank estimator (lambdarank)."""

    groupCol = Param("groupCol", "query group id column", "groupId")
    maxPosition = Param("maxPosition", "NDCG truncation position", 20, int)
    evalAt = Param("evalAt", "NDCG@k positions for eval", (1, 2, 3, 4, 5))
    labelGain = Param("labelGain",
                      "relevance gain per integer label (default 2^l - 1)", None)
    sigma = Param("sigma", "lambdarank sigmoid steepness", 1.0, float)

    def __init__(self, **kw):
        kw.setdefault("objective", "lambdarank")
        super().__init__(**kw)

    def _objective_name(self) -> str:
        return "lambdarank"

    def _fit(self, df: DataFrame) -> "LightGBMRankerModel":
        x, y, w, is_valid, init_score = self._extract_xyw(df)
        gcol = self.get("groupCol")
        if gcol not in df:
            raise ValueError(f"groupCol {gcol!r} not in DataFrame")
        groups = np.asarray(df[gcol])
        if np.asarray(y).min() < 0:
            raise ValueError("ranking labels must be non-negative integers")
        booster = self._train_booster(x, y, w, is_valid, 1,
                                      "lambdarank", init_score, groups)
        return self._propagate_model_params(LightGBMRankerModel(booster))

    def _store_fit_spec(self, store):
        """Out-of-core lambdarank: the group-id column streams from the
        store (one int per row — read_column is a designated assembly
        point); label non-negativity checks the manifest's exact
        label_min stat instead of a label pass."""
        from ...io import shardstore as sstore
        if sstore.GROUP not in store.columns:
            raise ValueError(
                f"LightGBMRanker needs a group column in the shard store "
                f"at {store.path} (write_store(..., group=...))")
        stats = store.stats or {}
        lmin = stats.get("label_min")
        if lmin is not None and lmin < 0:
            raise ValueError("ranking labels must be non-negative integers")
        return "lambdarank", 1, sstore.read_column(store, sstore.GROUP)

    def _make_store_model(self, booster):
        return self._propagate_model_params(LightGBMRankerModel(booster))

    def _make_config(self, num_class, axis_name, objective=None,
                     has_init_score=False):
        cfg = super()._make_config(num_class, axis_name, objective,
                                   has_init_score)
        label_gain = self.get("labelGain")
        eval_at = self.get("evalAt")
        return cfg._replace(
            max_position=self.get("maxPosition"),
            eval_at=int(eval_at[0]) if eval_at else 0,
            sigma=self.get("sigma"),
            label_gain_table=tuple(label_gain) if label_gain else None,
            max_label=(len(label_gain) - 1) if label_gain else 31)


class LightGBMRankerModel(LightGBMModelBase):
    """Fitted ranker; prediction column = raw ranking score."""

    def transform(self, df: DataFrame) -> DataFrame:
        x = dense_matrix(df[self.get("featuresCol")])
        scores = np.asarray(self.booster.raw_predict(x)).reshape(len(x))
        out = df.with_column(self.get("predictionCol"), scores)
        return self._add_optional_cols(out, x)

    @staticmethod
    def load_native_model_from_file(path: str) -> "LightGBMRankerModel":
        from .native_format import parse_model_file
        return LightGBMRankerModel(parse_model_file(path))

    @staticmethod
    def load_native_model_from_string(s: str) -> "LightGBMRankerModel":
        from .native_format import parse_model_string
        return LightGBMRankerModel(parse_model_string(s))

    loadNativeModelFromFile = load_native_model_from_file
    loadNativeModelFromString = load_native_model_from_string
