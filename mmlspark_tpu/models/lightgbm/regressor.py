"""LightGBMRegressor / LightGBMRegressionModel.

Reference: lightgbm/LightGBMRegressor.scala:29-139 — objectives incl. quantile
(`alpha`) and tweedie (`tweedieVariancePower`).
"""

from __future__ import annotations

import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame, dense_matrix
from .base import LightGBMModelBase, LightGBMParamsBase


class LightGBMRegressor(LightGBMParamsBase):

    def __init__(self, **kw):
        super().__init__(**kw)
        if not self.is_set("objective"):
            self.set("objective", "regression")

    def _objective_name(self) -> str:
        return self.get("objective")

    def _fit(self, df: DataFrame) -> "LightGBMRegressionModel":
        x, y, w, is_valid, init_score = self._extract_xyw(df)
        booster = self._train_booster(x, np.asarray(y, np.float64), w,
                                      is_valid, 1, init_score=init_score)
        return self._propagate_model_params(LightGBMRegressionModel(booster))

    def _make_store_model(self, booster):
        return self._propagate_model_params(LightGBMRegressionModel(booster))


class LightGBMRegressionModel(LightGBMModelBase):

    def transform(self, df: DataFrame) -> DataFrame:
        x = dense_matrix(df[self.get("featuresCol")])
        pred = self.booster.score(x)
        out = df.with_column(self.get("predictionCol"),
                             np.asarray(pred, np.float64))
        return self._add_optional_cols(out, x)

    @staticmethod
    def load_native_model_from_file(path: str) -> "LightGBMRegressionModel":
        from .native_format import parse_model_string
        with open(path) as f:
            return LightGBMRegressionModel(booster=parse_model_string(f.read()))

    @staticmethod
    def load_native_model_from_string(s: str) -> "LightGBMRegressionModel":
        from .native_format import parse_model_string
        return LightGBMRegressionModel(booster=parse_model_string(s))

    loadNativeModelFromFile = load_native_model_from_file
    loadNativeModelFromString = load_native_model_from_string
