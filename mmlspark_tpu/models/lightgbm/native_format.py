"""Parser for the LightGBM text model format -> Booster.

Reference analogue: `loadNativeModelFromFile/String`
(lightgbm/LightGBMClassifier.scala:178-195, LightGBMBooster model-string constructor
LightGBMBooster.scala:12-37). Enables interchange with upstream LightGBM: models trained
here export via Booster.model_string() and models trained by LightGBM load here.

Node trees are converted to the slot/replay representation used by the jit prediction
programs (ops/boosting.py `Tree`): BFS over internal nodes guarantees parents are replayed
before children, and each step's right child takes slot step+1.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List

import numpy as np

from ...ops.boosting import Tree
from .booster import Booster


def _parse_tree_block(lines: Dict[str, str]):
    num_leaves = int(lines["num_leaves"])
    if num_leaves == 1:
        lv = np.array([float(v) for v in lines["leaf_value"].split()])
        lcnt = (np.array([float(v) for v in lines["leaf_count"].split()])
                if "leaf_count" in lines else np.zeros(1))
        return num_leaves, (np.zeros(0, int), np.zeros(0), np.zeros(0, int),
                            np.zeros(0, int), lv, lcnt,
                            np.zeros(0, bool), np.zeros((0, 1), bool),
                            np.zeros(0, bool), np.zeros(0, int),
                            np.zeros(0))
    sf = np.array([int(v) for v in lines["split_feature"].split()])
    thr = np.array([float(v) for v in lines["threshold"].split()])
    lc = np.array([int(v) for v in lines["left_child"].split()])
    rc = np.array([int(v) for v in lines["right_child"].split()])
    lv = np.array([float(v) for v in lines["leaf_value"].split()])
    lcnt = (np.array([float(v) for v in lines["leaf_count"].split()])
            if "leaf_count" in lines else np.zeros(len(lv)))
    gain = (np.array([float(v) for v in lines["split_gain"].split()])
            if "split_gain" in lines else np.zeros(len(sf)))
    # decision_type (upstream tree.h): bit0 categorical, bit1 default_left,
    # bits2-3 missing type (0 None, 1 Zero, 2 NaN)
    dec = (np.array([int(v) for v in lines["decision_type"].split()])
           if "decision_type" in lines else np.full(len(sf), 2))
    is_cat = (dec & 1).astype(bool)
    default_left = ((dec >> 1) & 1).astype(bool)
    missing_type = (dec >> 2) & 3
    n_splits = len(sf)
    if is_cat.any():
        cb = np.array([int(v) for v in lines["cat_boundaries"].split()])
        cw = np.array([int(v) for v in lines["cat_threshold"].split()],
                      dtype=np.uint64)
        n_words = int((cb[1:] - cb[:-1]).max()) if len(cb) > 1 else 1
        width = n_words * 32
        masks = np.zeros((n_splits, width), bool)
        for s in range(n_splits):
            if not is_cat[s]:
                continue
            ci = int(thr[s])
            words = cw[cb[ci]:cb[ci + 1]]
            for wi, word in enumerate(words):
                for bit in range(32):
                    if int(word) >> bit & 1:
                        masks[s, wi * 32 + bit] = True
    else:
        masks = np.zeros((n_splits, 1), bool)
    return num_leaves, (sf, thr, lc, rc, lv, lcnt, is_cat, masks,
                        default_left, missing_type, gain)


def _nodes_to_slots(num_leaves: int, arrays, max_leaves: int,
                    mask_width: int = 1):
    """Convert LightGBM node arrays to padded slot/replay arrays."""
    (sf, thr, lc, rc, lv, lcnt, node_cat, node_masks, node_dl,
     node_mt, node_gain) = arrays
    n_splits = len(sf)
    lcap = max_leaves
    split_slot = np.zeros(lcap - 1, np.int32)
    split_feat = np.zeros(lcap - 1, np.int32)
    split_bin = np.zeros(lcap - 1, np.int32)
    split_valid = np.zeros(lcap - 1, bool)
    split_gain = np.zeros(lcap - 1, np.float32)
    split_is_cat = np.zeros(lcap - 1, bool)
    split_mask = np.zeros((lcap - 1, mask_width), bool)
    split_dl = np.zeros(lcap - 1, bool)
    split_mt = np.zeros(lcap - 1, np.int32)
    thresholds = np.zeros(lcap - 1, np.float64)
    leaf_value = np.zeros(lcap, np.float32)
    leaf_count = np.zeros(lcap, np.float32)

    if n_splits == 0:
        leaf_value[0] = lv[0]
        leaf_count[0] = lcnt[0]
        return Tree(split_slot, split_feat, split_bin, split_valid, split_gain,
                    leaf_value, leaf_count, split_is_cat,
                    split_mask, split_dl, split_mt), thresholds

    slot_of_node = {0: 0}
    step = 0
    queue = deque([0])
    while queue:
        node = queue.popleft()
        slot = slot_of_node[node]
        split_slot[step] = slot
        split_feat[step] = sf[node]
        thresholds[step] = thr[node]
        split_valid[step] = True
        split_gain[step] = node_gain[node]
        split_dl[step] = bool(node_dl[node])
        split_mt[step] = int(node_mt[node])
        if node_cat[node]:
            split_is_cat[step] = True
            w = min(node_masks.shape[1], mask_width)
            split_mask[step, :w] = node_masks[node][:w]
            # categorical threshold is a cat-table index, meaningless as a value
            thresholds[step] = 0.0
        new_slot = step + 1
        left, right = lc[node], rc[node]
        if left >= 0:
            slot_of_node[left] = slot
            queue.append(left)
        else:
            leaf_value[slot] = lv[~left]
            leaf_count[slot] = lcnt[~left]
        if right >= 0:
            slot_of_node[right] = new_slot
            queue.append(right)
        else:
            leaf_value[new_slot] = lv[~right]
            leaf_count[new_slot] = lcnt[~right]
        step += 1
    return Tree(split_slot, split_feat, split_bin, split_valid, split_gain,
                leaf_value, leaf_count, split_is_cat, split_mask,
                split_dl, split_mt), thresholds


def parse_model_string(s: str) -> Booster:
    header: Dict[str, str] = {}
    tree_blocks: List[Dict[str, str]] = []
    cur: Dict[str, str] = header
    for line in s.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("Tree="):
            cur = {}
            tree_blocks.append(cur)
            continue
        if line.startswith("end of trees"):
            cur = {}
            continue
        if "=" in line:
            k, _, v = line.partition("=")
            cur[k] = v

    num_class = int(header.get("num_class", "1"))
    ntpi = int(header.get("num_tree_per_iteration", "1"))
    num_features = int(header.get("max_feature_idx", "0")) + 1
    obj_raw = header.get("objective", "regression")
    objective = obj_raw.split()[0]
    feature_names = header.get("feature_names", "").split() or None

    parsed = [_parse_tree_block(tb) for tb in tree_blocks]
    max_leaves = max((p[0] for p in parsed), default=1)
    max_leaves = max(max_leaves, 2)
    mask_width = max((arrs[7].shape[1] for _, arrs in parsed), default=1)
    slot_trees = [_nodes_to_slots(nl, arrs, max_leaves, mask_width)
                  for nl, arrs in parsed]

    trees = Tree(*[np.stack([np.asarray(getattr(t, f)) for t, _ in slot_trees])
                   for f in Tree._fields])
    thresholds = np.stack([thr for _, thr in slot_trees])

    multiclass = ntpi > 1
    if multiclass:
        t = len(slot_trees) // ntpi
        trees = Tree(*[a.reshape(t, ntpi, *a.shape[1:]) for a in trees])
        thresholds = thresholds.reshape(t, ntpi, -1)
        init = np.zeros(ntpi, np.float32)
    else:
        init = np.float32(0.0)

    return Booster(trees, thresholds, init, objective,
                   num_class if multiclass else 1, num_features,
                   bin_mapper=None, feature_names=feature_names)
