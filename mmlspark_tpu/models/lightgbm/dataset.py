"""Reusable binned training dataset — upstream LightGBM's `Dataset` role.

Reference: lightgbm/LightGBMDataset.scala:12-101 — the native dataset handle
built once from marshalled rows (`LGBM_DatasetCreateFromMat`) and reused
across boosters; upstream forbids changing bin parameters after construction
("Cannot change max_bin after constructed Dataset").

TPU design: the expensive reusable artifact is the host-side precompute —
feature-matrix extraction plus quantile binning (BinMapper + the C++
threshold kernel). `LightGBMDataset` runs that once and hands the cached
binned uint8 matrix to every subsequent fit, so repeated trainings over the
same data (TuneHyperparameters sweeps, FindBestModel comparisons, continued
training) skip re-binning entirely:

    ds = LightGBMDataset(df, clf)
    models = clf.fit(ds, paramMaps)      # bins computed once, not len(maps)x

The wrapper delegates column access to the underlying DataFrame, so label /
weight / validation / group columns resolve exactly as with a plain fit(df).
Note one deliberate semantic difference under numBatches: batches reuse this
dataset's full-data bin edges (consistent bins across batches), while a
plain fit(df) re-fits edges per batch like the reference's per-batch
Dataset construction (LightGBMBase.scala:29-50).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ...core.dataframe import DataFrame


class LightGBMDataset:
    """Precomputed binned features for repeated GBDT fits.

    Bin parameters (maxBin, binSampleCount, seed, categorical slots,
    maxBinByFeature, useMissing) and the features column are frozen from the
    estimator at construction; fitting with an estimator whose settings
    disagree raises, mirroring upstream's constructed-Dataset contract.
    """

    def __init__(self, df: DataFrame, estimator):
        self._df = df
        self._features_col = estimator.get("featuresCol")
        self._config = estimator._bin_config()
        self._x = estimator._extract_features(df)
        self._pack = estimator._fit_binning(self._x)

    # -- DataFrame delegation (labels/weights/groups resolve transparently)
    @property
    def dataframe(self) -> DataFrame:
        return self._df

    def __getitem__(self, key):
        return self._df[key]

    def __contains__(self, key) -> bool:
        return key in self._df

    def __len__(self) -> int:
        return len(self._df)

    # -- estimator-facing surface
    def pack_for(self, estimator) -> Tuple[np.ndarray, tuple]:
        """Validate the estimator against this dataset's frozen bin config
        and return (features_matrix, (bin_mapper, binned, missing_idx))."""
        if estimator.get("featuresCol") != self._features_col:
            raise ValueError(
                f"estimator featuresCol {estimator.get('featuresCol')!r} != "
                f"the column this LightGBMDataset was built from "
                f"({self._features_col!r})")
        cfg = estimator._bin_config()
        if cfg != self._config:
            names = ("maxBin", "binSampleCount", "seed",
                     "categorical slots", "maxBinByFeature", "useMissing")
            diffs = [n for n, a, b in zip(names, cfg, self._config) if a != b]
            raise ValueError(
                "bin parameters cannot change after a LightGBMDataset is "
                f"constructed (differs in: {', '.join(diffs)}); build a new "
                "dataset — upstream: 'Cannot change max_bin after "
                "constructed Dataset'")
        return self._x, self._pack
