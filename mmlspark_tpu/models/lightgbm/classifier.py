"""LightGBMClassifier / LightGBMClassificationModel.

Reference: lightgbm/LightGBMClassifier.scala:24-195 — ProbabilisticClassifier emitting
raw/probability/prediction (and leaf-prediction) columns; numClass inferred from data
(LightGBMClassifier.scala:39); loadNativeModelFromFile/String loaders.

The transform path is batched jit inference over the whole column — replacing the
reference's per-row UDF -> JNI `LGBM_BoosterPredictForMatSingle` hot loop
(LightGBMClassifier.scala:100-142, flagged in SURVEY.md §3.1).
"""

from __future__ import annotations

import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame, dense_matrix
from .base import LightGBMModelBase, LightGBMParamsBase
from .booster import Booster


class LightGBMClassifier(LightGBMParamsBase, _p.HasProbabilityCol,
                         _p.HasRawPredictionCol):

    isUnbalance = _p.Param(
        "isUnbalance",
        "binary only: reweight training rows so both classes carry equal "
        "total weight (upstream is_unbalance: positives scaled by "
        "sum_neg/sum_pos; LightGBMClassifier.scala:32-36)", False)

    def __init__(self, **kw):
        super().__init__(**kw)
        if not self.is_set("objective"):
            self.set("objective", "binary")

    def _fit(self, df: DataFrame) -> "LightGBMClassificationModel":
        x, y, w, is_valid, init_score = self._extract_xyw(df)
        labels = np.asarray(y, np.float64)
        classes = np.unique(labels[~np.isnan(labels)]).astype(int)
        num_class = int(classes.max()) + 1 if classes.size else 2
        # numClass inferred from data (LightGBMClassifier.scala:39); resolved
        # locally so fit() never mutates the estimator's own params
        if num_class <= 2:
            objective = "binary"
        elif self.get("objective") in ("multiclassova", "multiclass_ova",
                                       "ova", "ovr"):
            objective = "multiclassova"
        else:
            objective = "multiclass"
        if num_class <= 2:
            num_class = 2
        if self.get("isUnbalance"):
            if objective != "binary":
                raise ValueError("isUnbalance applies to binary objectives "
                                 "only (upstream LightGBM restriction)")
            train_mask = ~np.asarray(is_valid, bool)
            pos = float(np.sum(w[train_mask & (labels > 0.5)]))
            neg = float(np.sum(w[train_mask & (labels <= 0.5)]))
            if pos > 0 and neg > 0:
                w = np.where(labels > 0.5, w * (neg / pos), w).astype(w.dtype)
        booster = self._train_booster(
            x, labels.astype(np.int32) if num_class > 2 else labels,
            w, is_valid, num_class if num_class > 2 else 1,
            objective=objective, init_score=init_score)
        model = LightGBMClassificationModel(booster=booster, num_class=num_class)
        for p in ("probabilityCol", "rawPredictionCol"):
            model.set(p, self.get(p))
        return self._propagate_model_params(model)

    def _store_fit_spec(self, store):
        """Out-of-core numClass inference: the in-memory path unique()s
        the full label array; the store manifest's exact whole-pass
        label_max stat gives the same answer without a label pass
        (labels are dense class ids 0..C-1, the upstream contract)."""
        if self.get("isUnbalance"):
            raise ValueError(
                "isUnbalance is not supported when fitting from a shard "
                "store (it needs a full-label pass for class weight "
                "sums); pre-weight rows in the store's weight column")
        stats = store.stats or {}
        lmax = stats.get("label_max")
        if lmax is None:
            raise ValueError(
                f"shard store at {store.path} has no label stats in its "
                "manifest; rewrite it with ShardStoreWriter")
        num_class = int(lmax) + 1
        if num_class <= 2:
            return "binary", 1, None
        if self.get("objective") in ("multiclassova", "multiclass_ova",
                                     "ova", "ovr"):
            return "multiclassova", num_class, None
        return "multiclass", num_class, None

    def _make_store_model(self, booster):
        k = booster.num_class if booster.multiclass else 2
        model = LightGBMClassificationModel(booster=booster, num_class=k)
        for p in ("probabilityCol", "rawPredictionCol"):
            model.set(p, self.get(p))
        return self._propagate_model_params(model)


class LightGBMClassificationModel(LightGBMModelBase, _p.HasProbabilityCol,
                                  _p.HasRawPredictionCol):
    numClass = _p.Param("numClass", "number of classes", 2, int)

    def __init__(self, booster=None, num_class: int = 2, **kw):
        super().__init__(booster=booster, **kw)
        self.set("numClass", num_class)

    def get_actual_num_classes(self) -> int:
        """getActualNumClasses (LightGBMClassifier.scala model surface)."""
        return self.get("numClass")

    getActualNumClasses = get_actual_num_classes

    def transform(self, df: DataFrame) -> DataFrame:
        x = dense_matrix(df[self.get("featuresCol")])
        raw = self.booster.raw_predict(x)
        if raw.ndim == 1:  # binary: margins -> [p0, p1]
            prob1 = 1.0 / (1.0 + np.exp(-raw))
            probs = np.stack([1 - prob1, prob1], axis=1)
            raws = np.stack([-raw, raw], axis=1)
        elif self.booster.objective == "multiclassova":
            # one-vs-all: per-class sigmoids, renormalized (upstream ova link)
            p = 1.0 / (1.0 + np.exp(-raw))
            probs = p / np.maximum(p.sum(axis=1, keepdims=True), 1e-15)
            raws = raw
        else:
            z = raw - raw.max(axis=1, keepdims=True)
            e = np.exp(z)
            probs = e / e.sum(axis=1, keepdims=True)
            raws = raw
        pred = probs.argmax(axis=1).astype(np.float64)
        out = (df.with_column(self.get("rawPredictionCol"), raws)
                 .with_column(self.get("probabilityCol"), probs)
                 .with_column(self.get("predictionCol"), pred))
        return self._add_optional_cols(out, x)

    # loaders — reference: LightGBMClassifier.scala:178-195
    @staticmethod
    def load_native_model_from_file(path: str) -> "LightGBMClassificationModel":
        from .native_format import parse_model_string
        with open(path) as f:
            booster = parse_model_string(f.read())
        k = booster.num_class if booster.multiclass else 2
        return LightGBMClassificationModel(booster=booster, num_class=k)

    @staticmethod
    def load_native_model_from_string(s: str) -> "LightGBMClassificationModel":
        from .native_format import parse_model_string
        booster = parse_model_string(s)
        k = booster.num_class if booster.multiclass else 2
        return LightGBMClassificationModel(booster=booster, num_class=k)

    loadNativeModelFromFile = load_native_model_from_file
    loadNativeModelFromString = load_native_model_from_string
