"""TreeSHAP — exact per-feature contribution values for GBDT predictions.

Reference analogue: LightGBM's `C_API_PREDICT_CONTRIB` SHAP path reached through
`featuresShapCol` (lightgbm/LightGBMBooster.scala:218-228 `featuresShap`,
LightGBMModelMethods.scala getFeatureShaps). The C++ core implements Lundberg et al.'s
path-dependent TreeSHAP; this is the same algorithm over the slot-tree node arrays.

Output layout matches LightGBM predict(contrib=True): [N, F+1] with the expected value
in the last column (multiclass: [N, K*(F+1)]).

Host-side numpy by design: SHAP is an explanation path, not a training hot loop; trees
are tiny (<= num_leaves nodes) so recursion cost is O(rows * leaves * depth^2).
"""

from __future__ import annotations

import numpy as np

from ...ops.boosting import Tree
from .booster import _slots_to_nodes


class _NodeTree:
    """Flat node arrays for one tree with covers filled in."""

    def __init__(self, tree: Tree, thresholds: np.ndarray):
        sf, thr, lc, rc, lv, lcnt = _slots_to_nodes(tree, thresholds)
        self.split_feature = sf
        self.threshold = thr
        self.left = lc
        self.right = rc
        self.leaf_value = lv
        self.leaf_count = lcnt
        self.n_internal = len(sf)
        # node id == split step, so categorical info maps 1:1
        self.is_cat = np.asarray(tree.split_is_cat[:self.n_internal]).astype(bool)
        self.cat_mask = np.asarray(tree.split_mask[:self.n_internal]).astype(bool)
        self.default_left = np.asarray(
            tree.split_default_left[:self.n_internal]).astype(bool)
        self.missing_type = np.asarray(
            tree.split_missing_type[:self.n_internal]).astype(int)
        if self.leaf_count.sum() <= 0:
            # models parsed without leaf_count (older exports): uniform covers
            # are the only honest prior — all-zero covers would silently zero
            # every SHAP value
            self.leaf_count = np.ones_like(self.leaf_count)
        # cover per internal node = sum of leaf counts beneath it
        self.cover = np.zeros(self.n_internal)
        if self.n_internal:
            self._fill_cover(0)
        self.total = self.cover[0] if self.n_internal else float(lcnt[0])

    def _fill_cover(self, node: int) -> float:
        c = 0.0
        for child in (self.left[node], self.right[node]):
            if child >= 0:
                c += self._fill_cover(child)
            else:
                c += float(self.leaf_count[~child])
        self.cover[node] = c
        return c

    def child_cover(self, child: int) -> float:
        return self.cover[child] if child >= 0 else float(
            self.leaf_count[~child])

    def goes_left(self, node: int, xv: float) -> bool:
        if self.is_cat[node]:
            code = int(xv) if np.isfinite(xv) else 0
            if code < 0 or code >= self.cat_mask.shape[1]:
                return False  # outside the bitset -> right (LightGBM semantics)
            return bool(self.cat_mask[node, code])
        # upstream numerical_decision (tree.h) — the SAME routing as
        # tree_apply_raw, so SHAP contributions sum to the actual prediction
        # on rows with missing values
        mt = self.missing_type[node]
        is_nan = bool(np.isnan(xv))
        x0 = 0.0 if is_nan else xv
        if (mt == 2 and is_nan) or (mt == 1 and (is_nan or abs(x0) <= 1e-35)):
            return bool(self.default_left[node])
        return x0 <= self.threshold[node]

    def value(self, node: int) -> float:
        """Expected leaf value of the subtree (cover-weighted)."""
        if node < 0:
            return float(self.leaf_value[~node])
        lw = self.child_cover(self.left[node])
        rw = self.child_cover(self.right[node])
        tot = max(lw + rw, 1e-12)
        return (lw * self.value(self.left[node])
                + rw * self.value(self.right[node])) / tot


def _tree_shap_row(nt: _NodeTree, x: np.ndarray, phi: np.ndarray) -> None:
    """Path-dependent TreeSHAP (Lundberg et al. 2018, Algorithm 2) for one row."""
    if nt.n_internal == 0:
        return

    # unique path is a list of dicts-as-arrays: d (feature), z (zero fraction),
    # o (one fraction), w (pweight)
    def extend(path, pz, po, pi):
        # deep copy: the caller reuses its path for the sibling subtree
        path = [row[:] for row in path] + [[pi, pz, po, 0.0]]
        l = len(path)
        path[l - 1][3] = 1.0 if l == 1 else 0.0
        for i in range(l - 2, -1, -1):
            path[i + 1][3] += po * path[i][3] * (i + 1) / l
            path[i][3] = pz * path[i][3] * (l - 1 - i) / l
        return path

    def unwind(path, i):
        l = len(path)
        po, pz = path[i][2], path[i][1]
        n = path[l - 1][3]
        path = [row[:] for row in path]
        for j in range(l - 2, -1, -1):
            if po != 0:
                t = path[j][3]
                path[j][3] = n * l / ((j + 1) * po)
                n = t - path[j][3] * pz * (l - 1 - j) / l
            else:
                path[j][3] = path[j][3] * l / (pz * (l - 1 - j))
        # drop element i: d/z/o shift down one; weights keep their position
        for j in range(i, l - 1):
            path[j][0], path[j][1], path[j][2] = (
                path[j + 1][0], path[j + 1][1], path[j + 1][2])
        return path[: l - 1]

    def unwound_sum(path, i):
        l = len(path)
        po, pz = path[i][2], path[i][1]
        total = 0.0
        if po != 0:
            n = path[l - 1][3]
            for j in range(l - 2, -1, -1):
                t = n / ((j + 1) * po)
                total += t
                n = path[j][3] - t * pz * (l - 1 - j)
        else:
            for j in range(l - 2, -1, -1):
                total += path[j][3] / (pz * (l - 1 - j))
        return total * l

    def recurse(node, path, pz, po, pi):
        path = extend(path, pz, po, pi)
        if node < 0:  # leaf
            v = float(nt.leaf_value[~node])
            for i in range(1, len(path)):
                w = unwound_sum(path, i)
                phi[path[i][0]] += w * (path[i][2] - path[i][1]) * v
            return
        f = int(nt.split_feature[node])
        hot, cold = ((nt.left[node], nt.right[node])
                     if nt.goes_left(node, x[f])
                     else (nt.right[node], nt.left[node]))
        iz, io_ = 1.0, 1.0
        k = next((i for i in range(1, len(path)) if path[i][0] == f), None)
        if k is not None:
            iz, io_ = path[k][1], path[k][2]
            path = unwind(path, k)
        cov = max(nt.child_cover(nt.left[node]) +
                  nt.child_cover(nt.right[node]), 1e-12)
        recurse(hot, path, iz * nt.child_cover(hot) / cov, io_, f)
        recurse(cold, path, iz * nt.child_cover(cold) / cov, 0.0, f)

    recurse(0, [], 1.0, 1.0, -1)


def tree_shap(trees_list, thresholds_list, x: np.ndarray,
              num_features: int, init_score: float) -> np.ndarray:
    """SHAP contributions for a stack of single-output trees.

    trees_list: iterable of (Tree, thresholds) per iteration.
    Returns [N, F+1]; column F is the expected value (base + sum of tree means).
    """
    x = np.asarray(x, np.float64)
    n = x.shape[0]
    phi = np.zeros((n, num_features + 1))
    phi[:, -1] = init_score
    for tree, thr in zip(trees_list, thresholds_list):
        nt = _NodeTree(tree, np.asarray(thr))
        phi[:, -1] += nt.value(0) if nt.n_internal else float(nt.leaf_value[0])
        for r in range(n):
            row_phi = np.zeros(num_features + 1)
            _tree_shap_row(nt, x[r], row_phi)
            phi[r, :num_features] += row_phi[:num_features]
    return phi
