"""IsolationForest — unsupervised anomaly detection.

Reference: isolationforest/IsolationForest.scala:17-60, a thin facade over
`com.linkedin.isolation-forest` (JVM): per-tree subsampled random splits,
anomaly score 2^(-E[pathlen]/c(n)), threshold from `contamination`.

TPU design: trees build on host (each is log2(maxSamples) deep over a 256-row
subsample — trivially cheap); SCORING is the hot path and runs as one jitted
program: trees stack into padded arrays [T, nodes] and every row walks all
trees in lockstep via a depth-bounded gather loop (no recursion, no ragged
work).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model


def _c_factor(n: float) -> float:
    """Average BST unsuccessful-search path length c(n)."""
    if n <= 1:
        return 0.0
    h = math.log(n - 1) + 0.5772156649
    return 2.0 * h - 2.0 * (n - 1) / n


def _build_tree(x: np.ndarray, rng: np.random.Generator, max_depth: int):
    """Array-form isolation tree over subsample x. Returns (feature, threshold,
    left, right, size) with -1 children for leaves."""
    cap = 2 ** (max_depth + 1)
    feature = np.full(cap, -1, np.int32)
    threshold = np.zeros(cap, np.float32)
    left = np.full(cap, -1, np.int32)
    right = np.full(cap, -1, np.int32)
    size = np.zeros(cap, np.float32)
    next_free = [1]

    stack = [(0, np.arange(len(x)), 0)]
    while stack:
        node, idx, depth = stack.pop()
        size[node] = len(idx)
        if depth >= max_depth or len(idx) <= 1:
            continue
        sub = x[idx]
        spans = sub.max(0) - sub.min(0)
        live = np.flatnonzero(spans > 0)
        if live.size == 0:
            continue
        f = int(rng.choice(live))
        lo, hi = sub[:, f].min(), sub[:, f].max()
        t = float(rng.uniform(lo, hi))
        go_left = sub[:, f] < t
        l_node, r_node = next_free[0], next_free[0] + 1
        next_free[0] += 2
        feature[node] = f
        threshold[node] = t
        left[node] = l_node
        right[node] = r_node
        stack.append((l_node, idx[go_left], depth + 1))
        stack.append((r_node, idx[~go_left], depth + 1))
    used = next_free[0]
    return (feature[:used], threshold[:used], left[:used], right[:used],
            size[:used])


class _Forest(NamedTuple):
    feature: np.ndarray    # [T, nodes]
    threshold: np.ndarray
    left: np.ndarray
    right: np.ndarray
    size: np.ndarray
    max_depth: int
    sub_sample: int


@partial(jax.jit, static_argnames=("max_depth",))
def _path_lengths(feature, threshold, left, right, size, x, max_depth: int):
    """Average path length of each row over all trees. Inputs [T, nodes];
    x [N, F]. Depth-bounded lockstep walk: every row advances one level per
    step across all trees simultaneously."""
    t = feature.shape[0]
    n = x.shape[0]
    node = jnp.zeros((n, t), jnp.int32)
    depth_acc = jnp.zeros((n, t), jnp.float32)
    t_idx = jnp.arange(t)

    def body(_, carry):
        node, depth_acc = carry
        feat = feature[t_idx[None, :], node]              # [N,T]
        is_leaf = feat < 0
        thr = threshold[t_idx[None, :], node]
        xv = jnp.take_along_axis(x, jnp.maximum(feat, 0), axis=1)  # [N,T]
        go_left = xv < thr
        nxt = jnp.where(go_left, left[t_idx[None, :], node],
                        right[t_idx[None, :], node])
        node = jnp.where(is_leaf, node, nxt)
        depth_acc = depth_acc + jnp.where(is_leaf, 0.0, 1.0)
        return node, depth_acc

    node, depth_acc = jax.lax.fori_loop(0, max_depth + 1, body,
                                        (node, depth_acc))
    # leaf adjustment: + c(size) for unfinished isolation
    leaf_size = size[t_idx[None, :], node]
    ls = jnp.maximum(leaf_size, 1.0)
    h = jnp.log(jnp.maximum(ls - 1.0, 1e-9)) + 0.5772156649
    c_adj = jnp.where(ls > 1.0, 2.0 * h - 2.0 * (ls - 1.0) / ls, 0.0)
    return (depth_acc + c_adj).mean(axis=1)


class IsolationForest(Estimator, _p.HasFeaturesCol, _p.HasPredictionCol):
    numEstimators = _p.Param("numEstimators", "number of trees", 100, int)
    maxSamples = _p.Param("maxSamples", "subsample size per tree", 256, int)
    maxFeatures = _p.Param("maxFeatures", "feature fraction per tree", 1.0,
                           float)
    contamination = _p.Param("contamination",
                             "expected anomaly fraction (sets threshold); "
                             "0 = no labels, scores only", 0.0, float)
    scoreCol = _p.Param("scoreCol", "anomaly score column", "outlierScore")
    randomSeed = _p.Param("randomSeed", "rng seed", 1, int)

    def _fit(self, df: DataFrame) -> "IsolationForestModel":
        x = np.asarray(df[self.get("featuresCol")], np.float32)
        n, f = x.shape
        rng = np.random.default_rng(self.get("randomSeed"))
        sub = min(self.get("maxSamples"), n)
        max_depth = max(int(math.ceil(math.log2(max(sub, 2)))), 1)
        n_feat = max(int(round(self.get("maxFeatures") * f)), 1)
        trees = []
        for _ in range(self.get("numEstimators")):
            idx = rng.choice(n, sub, replace=False)
            feats = (np.arange(f) if n_feat >= f
                     else rng.choice(f, n_feat, replace=False))
            sample = x[idx][:, feats]
            fe, th, le, ri, si = _build_tree(sample, rng, max_depth)
            fe = np.where(fe >= 0, feats[np.maximum(fe, 0)], -1).astype(
                np.int32)
            trees.append((fe, th, le, ri, si))
        cap = max(len(t[0]) for t in trees)

        def pad(a, fill):
            return np.stack([
                np.concatenate([t, np.full(cap - len(t), fill, t.dtype)])
                for t in a])
        forest = _Forest(
            feature=pad([t[0] for t in trees], -1),
            threshold=pad([t[1] for t in trees], 0.0),
            left=pad([t[2] for t in trees], -1),
            right=pad([t[3] for t in trees], -1),
            size=pad([t[4] for t in trees], 0.0),
            max_depth=max_depth, sub_sample=sub)
        model = IsolationForestModel(forest=forest)
        for p in ("featuresCol", "predictionCol", "scoreCol"):
            model.set(p, self.get(p))
        contamination = self.get("contamination")
        if contamination > 0:
            scores = model._scores(x)
            model.set("threshold",
                      float(np.quantile(scores, 1.0 - contamination)))
        return model


class IsolationForestModel(Model, _p.HasFeaturesCol, _p.HasPredictionCol):
    scoreCol = _p.Param("scoreCol", "anomaly score column", "outlierScore")
    threshold = _p.Param("threshold", "score threshold for predicted label",
                         0.5, float)
    forest = _p.Param("forest", "stacked tree arrays", None, complex=True)

    def __init__(self, forest: Optional[_Forest] = None, **kw):
        super().__init__(**kw)
        if forest is not None:
            self.set("forest", forest)

    def _scores(self, x: np.ndarray) -> np.ndarray:
        fr = self.get("forest")
        if not isinstance(fr, _Forest):
            fr = _Forest(*fr)  # complex-param roundtrip may yield a tuple
        avg_path = np.asarray(_path_lengths(
            jnp.asarray(fr.feature), jnp.asarray(fr.threshold),
            jnp.asarray(fr.left), jnp.asarray(fr.right),
            jnp.asarray(fr.size), jnp.asarray(x, jnp.float32),
            int(fr.max_depth)))
        c = _c_factor(float(fr.sub_sample))
        return np.exp2(-avg_path / max(c, 1e-9))

    def transform(self, df: DataFrame) -> DataFrame:
        x = np.asarray(df[self.get("featuresCol")], np.float32)
        scores = self._scores(x)
        pred = (scores >= self.get("threshold")).astype(np.float64)
        return (df.with_column(self.get("scoreCol"), scores)
                  .with_column(self.get("predictionCol"), pred))
