"""Logistic / linear regression — full-batch jit training on the MXU.

Equivalent of the SparkML LogisticRegression / LinearRegression learners the
reference reaches through TrainClassifier/TrainRegressor
(train/TrainClassifier.scala:53-374). Training is L-BFGS-free by design: a
fixed-count Adam loop under `lax.scan` keeps the whole fit one XLA program —
static shapes, no host round-trips, matmul-dominated (batch x features x
classes rides the MXU in bf16-friendly sizes).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame
from ...core.pipeline import Estimator, Model


@partial(jax.jit, static_argnames=("num_class", "epochs", "lr"))
def _fit_logistic(x, y, w, num_class: int, epochs: int, lr: float,
                  reg_param: float):
    """Softmax regression via Adam under lax.scan. y: int32 [n]; w: [n]."""
    n, f = x.shape
    params0 = (jnp.zeros((f, num_class), jnp.float32),
               jnp.zeros((num_class,), jnp.float32))

    def loss_fn(params):
        wt, b = params
        logits = x @ wt + b
        logp = jax.nn.log_softmax(logits)
        nll = -(logp[jnp.arange(n), y] * w).sum() / jnp.maximum(w.sum(), 1e-9)
        return nll + reg_param * (wt * wt).sum()

    def step(carry, _):
        params, m, v, t = carry
        g = jax.grad(loss_fn)(params)
        t = t + 1
        m = jax.tree.map(lambda a, b_: 0.9 * a + 0.1 * b_, m, g)
        v = jax.tree.map(lambda a, b_: 0.999 * a + 0.001 * b_ * b_, v, g)
        mh = jax.tree.map(lambda a: a / (1 - 0.9 ** t), m)
        vh = jax.tree.map(lambda a: a / (1 - 0.999 ** t), v)
        params = jax.tree.map(
            lambda p, a, b_: p - lr * a / (jnp.sqrt(b_) + 1e-8), params, mh, vh)
        return (params, m, v, t), loss_fn(params)

    zeros = jax.tree.map(jnp.zeros_like, params0)
    (params, _, _, _), losses = jax.lax.scan(
        step, (params0, zeros, zeros, jnp.float32(0.0)), None, length=epochs)
    return params, losses


@partial(jax.jit, static_argnames=("epochs", "lr"))
def _fit_linear(x, y, w, epochs: int, lr: float, reg_param: float):
    n, f = x.shape
    params0 = (jnp.zeros((f,), jnp.float32), jnp.zeros((), jnp.float32))

    def loss_fn(params):
        wt, b = params
        pred = x @ wt + b
        mse = (w * (pred - y) ** 2).sum() / jnp.maximum(w.sum(), 1e-9)
        return mse + reg_param * (wt * wt).sum()

    def step(carry, _):
        params, m, v, t = carry
        g = jax.grad(loss_fn)(params)
        t = t + 1
        m = jax.tree.map(lambda a, b_: 0.9 * a + 0.1 * b_, m, g)
        v = jax.tree.map(lambda a, b_: 0.999 * a + 0.001 * b_ * b_, v, g)
        mh = jax.tree.map(lambda a: a / (1 - 0.9 ** t), m)
        vh = jax.tree.map(lambda a: a / (1 - 0.999 ** t), v)
        params = jax.tree.map(
            lambda p, a, b_: p - lr * a / (jnp.sqrt(b_) + 1e-8), params, mh, vh)
        return (params, m, v, t), None

    zeros = jax.tree.map(jnp.zeros_like, params0)
    (params, _, _, _), _ = jax.lax.scan(
        step, (params0, zeros, zeros, jnp.float32(0.0)), None, length=epochs)
    return params


def _standardize(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    mu = x.mean(axis=0)
    sd = x.std(axis=0)
    sd[sd < 1e-9] = 1.0
    return ((x - mu) / sd).astype(np.float32), mu, sd


class _LinearBase(Estimator, _p.HasFeaturesCol, _p.HasLabelCol,
                  _p.HasWeightCol, _p.HasPredictionCol):
    regParam = _p.Param("regParam", "L2 regularization", 0.0, float)
    maxIter = _p.Param("maxIter", "Adam iterations", 200, int)
    stepSize = _p.Param("stepSize", "Adam learning rate", 0.1, float)

    def _xyw(self, df: DataFrame):
        x = np.asarray(df[self.get("featuresCol")], np.float32)
        y = np.asarray(df[self.get("labelCol")], np.float64)
        wcol = self.get("weightCol")
        w = (np.asarray(df[wcol], np.float32) if wcol and wcol in df
             else np.ones(len(y), np.float32))
        return x, y, w

    @staticmethod
    def _pad_bucket(xs: np.ndarray, y: np.ndarray, w: np.ndarray,
                    bucket: int = 512):
        """Pad rows to a shape bucket so k-fold / resampled fits reuse the
        same compiled program (padded rows carry zero weight)."""
        rem = (-len(y)) % bucket
        if rem:
            xs = np.concatenate([xs, np.zeros((rem, xs.shape[1]), xs.dtype)])
            y = np.concatenate([y, np.zeros(rem, y.dtype)])
            w = np.concatenate([w, np.zeros(rem, np.float32)])
        return xs, y, w


class LogisticRegression(_LinearBase, _p.HasProbabilityCol,
                         _p.HasRawPredictionCol):
    def _fit(self, df: DataFrame) -> "LogisticRegressionModel":
        x, y, w = self._xyw(df)
        xs, mu, sd = _standardize(x)
        yi = y.astype(np.int32)
        k = max(int(yi.max()) + 1, 2)
        xs, yi, w = self._pad_bucket(xs, yi, w)
        (wt, b), _ = _fit_logistic(
            jnp.asarray(xs), jnp.asarray(yi), jnp.asarray(w), k,
            self.get("maxIter"), self.get("stepSize"),
            jnp.float32(self.get("regParam")))
        model = LogisticRegressionModel(
            coefficients=np.asarray(wt), intercept=np.asarray(b),
            mean=mu, scale=sd, num_class=k)
        for p in ("featuresCol", "predictionCol", "probabilityCol",
                  "rawPredictionCol"):
            model.set(p, self.get(p))
        return model


class LogisticRegressionModel(Model, _p.HasFeaturesCol, _p.HasPredictionCol,
                              _p.HasProbabilityCol, _p.HasRawPredictionCol):
    coefficients = _p.Param("coefficients", "weights [f,k]", None, complex=True)
    intercept = _p.Param("intercept", "bias [k]", None, complex=True)
    mean = _p.Param("mean", "feature standardization mean", None, complex=True)
    scale = _p.Param("scale", "feature standardization scale", None, complex=True)
    numClass = _p.Param("numClass", "number of classes", 2, int)

    def __init__(self, coefficients=None, intercept=None, mean=None,
                 scale=None, num_class: int = 2, **kw):
        super().__init__(**kw)
        if coefficients is not None:
            self._set(coefficients=coefficients, intercept=intercept,
                      mean=mean, scale=scale, numClass=num_class)

    def transform(self, df: DataFrame) -> DataFrame:
        x = np.asarray(df[self.get("featuresCol")], np.float32)
        xs = (x - self.get("mean")) / self.get("scale")
        logits = xs @ self.get("coefficients") + self.get("intercept")
        z = logits - logits.max(axis=1, keepdims=True)
        e = np.exp(z)
        probs = e / e.sum(axis=1, keepdims=True)
        return (df.with_column(self.get("rawPredictionCol"), logits)
                  .with_column(self.get("probabilityCol"), probs)
                  .with_column(self.get("predictionCol"),
                               probs.argmax(axis=1).astype(np.float64)))


class LinearRegression(_LinearBase):
    def _fit(self, df: DataFrame) -> "LinearRegressionModel":
        x, y, w = self._xyw(df)
        xs, mu, sd = _standardize(x)
        ym = float(np.average(y, weights=w))
        yc = (y - ym).astype(np.float32)
        xs, yc, w = self._pad_bucket(xs, yc, w)
        wt, b = _fit_linear(
            jnp.asarray(xs), jnp.asarray(yc),
            jnp.asarray(w), self.get("maxIter"), self.get("stepSize"),
            jnp.float32(self.get("regParam")))
        model = LinearRegressionModel(
            coefficients=np.asarray(wt), intercept=float(b) + ym,
            mean=mu, scale=sd)
        for p in ("featuresCol", "predictionCol"):
            model.set(p, self.get(p))
        return model


class LinearRegressionModel(Model, _p.HasFeaturesCol, _p.HasPredictionCol):
    coefficients = _p.Param("coefficients", "weights [f]", None, complex=True)
    intercept = _p.Param("intercept", "bias", 0.0, float)
    mean = _p.Param("mean", "feature standardization mean", None, complex=True)
    scale = _p.Param("scale", "feature standardization scale", None, complex=True)

    def __init__(self, coefficients=None, intercept: float = 0.0, mean=None,
                 scale=None, **kw):
        super().__init__(**kw)
        if coefficients is not None:
            self._set(coefficients=coefficients, intercept=float(intercept),
                      mean=mean, scale=scale)

    def transform(self, df: DataFrame) -> DataFrame:
        x = np.asarray(df[self.get("featuresCol")], np.float32)
        xs = (x - self.get("mean")) / self.get("scale")
        pred = xs @ self.get("coefficients") + self.get("intercept")
        return df.with_column(self.get("predictionCol"),
                              pred.astype(np.float64))
