"""Classic learners — TPU-jit equivalents of the SparkML algorithms the
reference wraps through TrainClassifier/TrainRegressor (train/TrainClassifier.scala:53-374:
LogisticRegression, DecisionTree/RandomForest/GBT, LinearRegression...).
Tree-family learners map onto the GBDT engine (models/lightgbm); the linear
family is here."""

from .linear import (LinearRegression, LinearRegressionModel,
                     LogisticRegression, LogisticRegressionModel)

__all__ = ["LogisticRegression", "LogisticRegressionModel",
           "LinearRegression", "LinearRegressionModel"]
