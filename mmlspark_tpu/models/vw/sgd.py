"""jit-compiled sparse linear SGD — the VowpalWabbit C++ core, the TPU way.

Reference behavior being replaced (vw/VowpalWabbitBase.scala:235-341 +
`vw-jni 8.8.1` C++): per-example online updates with adaptive (AdaGrad),
normalized (per-feature scale), and importance-invariant steps; L1/L2
regularization; multi-pass over a cache file; per-pass spanning-tree allreduce
of weights across workers (trainInternalDistributed, :401-429).

TPU design: examples are packed into fixed-width sparse batches
(models/vw/sparse.py) and a `lax.scan` walks minibatches, so one XLA program
runs the whole pass with static shapes. Exact per-example ordering is traded
for minibatch equivalence (SURVEY.md §7: "minibatched SGD with equivalence
tolerances rather than bit parity"). The spanning-tree allreduce becomes a
`lax.pmean` over the mesh data axis at the end of every pass.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class VWConfig(NamedTuple):
    num_features: int
    loss: str = "squared"          # "squared" | "logistic"
    learning_rate: float = 0.5     # VW -l default
    power_t: float = 0.5           # VW --power_t default
    initial_t: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    adaptive: bool = True          # VW default: adaptive+normalized+invariant
    normalized: bool = True
    invariant: bool = True
    num_passes: int = 1
    minibatch: int = 256
    use_constant: bool = True      # VW constant feature (--noconstant off)
    axis_name: Optional[str] = None  # set => per-pass pmean over this mesh axis
    # row-invariant index layout (dense feature columns, incl. their
    # interactions): every real row carries the SAME index vector, so the
    # per-step [B, k] scatter-add/max — whose indices then collide
    # TOTALLY, the TPU sort-based scatter's worst case — pre-reduces over
    # the batch axis to a [k] scatter with identical totals (addition
    # commutes; gather-after-scatter sees the same sums; padded rows
    # carry value 0 and contribute nothing either way). Set by the
    # estimator after checking the actual arrays; measured ~4 ms -> sub-ms
    # per minibatch step on chip at 2^18 features.
    shared_indices: bool = False
    # fused packed tables (ISSUE 16): pack w/g2/scale into ONE
    # [R, 2^b] table so a step issues ONE gather and ONE scatter instead
    # of up to three of each. The scale table's max-reduction is fused
    # into the single scatter-add as a first-occurrence delta (see
    # _fused_minibatch_step); the per-step table rate reads are emulated
    # locally with duplicate-index segment reductions, so a fused step
    # never re-gathers a table it just scattered. Resolved from the
    # estimator's fusedTables param (auto/on/off, auto = ladder rule
    # resolve_auto_fused).
    fused: bool = False


class VWState(NamedTuple):
    """Learner state as a pytree (the model 'weights file')."""
    w: jnp.ndarray        # [F] feature weights
    g2: jnp.ndarray       # [F] AdaGrad sum of squared gradients
    scale: jnp.ndarray    # [F] per-feature max |x| seen (normalized updates)
    bias: jnp.ndarray     # [] constant term (VW's constant feature)
    bias_g2: jnp.ndarray  # []
    t: jnp.ndarray        # [] example counter (importance-weighted)


def init_state(num_features: int) -> VWState:
    f = num_features
    return VWState(
        w=jnp.zeros((f,), jnp.float32),
        g2=jnp.zeros((f,), jnp.float32),
        scale=jnp.zeros((f,), jnp.float32),
        bias=jnp.zeros((), jnp.float32),
        bias_g2=jnp.zeros((), jnp.float32),
        t=jnp.zeros((), jnp.float32),
    )


def _loss_and_grad(loss: str, pred, y):
    """Returns (per-row loss, dloss/dpred). Labels: squared = real values,
    logistic = {-1,+1} (VW convention)."""
    if loss == "logistic":
        margin = y * pred
        lv = jnp.logaddexp(0.0, -margin)
        g = -y * jax.nn.sigmoid(-margin)
        return lv, g
    diff = pred - y
    return 0.5 * diff * diff, diff


def predict_batch(state: VWState, indices, values):
    """Margin for a padded sparse batch: sum_k w[idx]*val + bias."""
    return (state.w[indices] * values).sum(axis=-1) + state.bias


def _invariant_delta(loss: str, pred, y, xbar, h):
    """Karampatziakis-Langford importance-weight-aware update: the exact
    change in prediction after following the gradient flow
    p'(tau) = -xbar * loss'(p) for time h (the importance weight), where
    xbar = sum_i r_i x_i^2 is the example's squared norm under the per-weight
    learning rates. Guarantees the update never overshoots the label.

    squared: p(h) = y + (p0-y) e^{-xbar h}  =>  dp = (y-p0)(1-e^{-xbar h}).
    logistic (y in {-1,+1}): with m = y p, m + e^m is conserved up to xbar*h:
    m1 + e^{m1} = m0 + e^{m0} + xbar h — solved by Newton (convex, explicit
    Euler start). Reference: VW gd.cc invariant updates (the reference forwards
    --invariant to C++, vw/VowpalWabbitBase.scala:139-169)."""
    if loss == "squared":
        return (y - pred) * (1.0 - jnp.exp(-xbar * h))
    # logistic
    m0 = jnp.clip(y * pred, -30.0, 30.0)
    em0 = jnp.exp(m0)
    target = xbar * h
    dm = target * jax.nn.sigmoid(-m0)  # explicit-Euler start (underestimate)
    for _ in range(3):                 # Newton on dm + e^{m0}(e^{dm}-1) = t
        e = em0 * (jnp.exp(jnp.clip(dm, -30.0, 30.0)) - 1.0)
        phi = dm + e - target
        dphi = 1.0 + em0 * jnp.exp(jnp.clip(dm, -30.0, 30.0))
        dm = dm - phi / dphi
    return y * dm


def _step_updates(cfg: VWConfig, pred, y, wt, values, gx, g_raw, g,
                  g2_view, scale_view, bias_g2, t):
    """Per-weight learning rates + the update step, given the POST-update
    gathered views of the adaptive/normalization tables. The unpacked and
    fused paths produce identical views (up to float reassociation in the
    duplicate-index sums), so this math is shared verbatim between them.

    Returns (step[B,k], bias_step)."""
    if cfg.adaptive:
        rate = cfg.learning_rate / (jnp.sqrt(g2_view) + 1e-6)
        bias_rate = cfg.learning_rate / (jnp.sqrt(bias_g2) + 1e-6)
    else:
        # decayed global rate: eta * (t0+1 / (t0+t))^power_t
        r = cfg.learning_rate * jnp.power(
            (cfg.initial_t + 1.0) / (cfg.initial_t + t + 1.0), cfg.power_t)
        rate = jnp.broadcast_to(r, values.shape)
        bias_rate = r
    if cfg.normalized:
        rate = rate / jnp.maximum(scale_view, 1e-6)

    if cfg.invariant:
        # exact importance-weight-aware update: compute the closed-form
        # prediction change dp and distribute it over the weights so the
        # example's prediction moves by exactly dp (never past the label).
        # The shared bias moves by the minibatch MEAN of per-example bias
        # steps, so its contribution to each example's xbar is bias_rate/B —
        # batch-total prediction change then matches batch-total dp exactly.
        xbar = (rate * values * values).sum(axis=-1)  # [B]
        if cfg.use_constant:
            xbar = xbar + bias_rate / values.shape[0]
        dp = _invariant_delta(cfg.loss, pred, y, xbar, wt)
        # dp/xbar is the per-unit step; as xbar->0 it limits to -g*h
        unit = jnp.where(xbar > 1e-12, dp / xbar, -g_raw * wt)
        step = -(rate * values) * unit[:, None]
        bias_step = -(bias_rate * unit).mean()
    else:
        step = rate * gx
        bias_step = bias_rate * g.mean()
    return step, bias_step


def _regularize(cfg: VWConfig, w):
    """L2 shrink + L1 truncated gradient over the whole weight table."""
    if cfg.l2 > 0.0:
        w = w * (1.0 - cfg.learning_rate * cfg.l2)
    if cfg.l1 > 0.0:
        thresh = cfg.learning_rate * cfg.l1
        w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - thresh, 0.0)
    return w


def _minibatch_step(cfg: VWConfig, state: VWState, batch):
    indices, values, y, wt = batch   # [B,k], [B,k], [B], [B]
    # shared-index mode (cfg.shared_indices): every real row carries the
    # index vector of row 0, so gathers read [k] once and scatters
    # pre-reduce over the batch axis — same totals, no total-collision
    # scatter. sidx stays None on the general path.
    sidx = indices[0] if cfg.shared_indices else None

    def gather(tab):
        """[B, k] per-row view of a [F] table on either path (shared mode
        reads the [k] slots once and broadcasts)."""
        return tab[sidx][None, :] if cfg.shared_indices else tab[indices]

    def scatter(tab, upd, op):
        """Accumulate a [B, k] update into a [F] table; shared mode
        pre-reduces the batch axis (sum for add, max for max) so the
        scatter is [k]-wide with no total-collision worst case."""
        if cfg.shared_indices:
            red = upd.sum(axis=0) if op == "add" else upd.max(axis=0)
            at = tab.at[sidx]
            return at.add(red) if op == "add" else at.max(red)
        at = tab.at[indices]
        return at.add(upd) if op == "add" else at.max(upd)

    pred = (gather(state.w) * values).sum(axis=-1) + state.bias
    lv, g_raw = _loss_and_grad(cfg.loss, pred, y)
    g = g_raw * wt                               # importance weight
    gx = g[:, None] * values                     # [B,k] per-weight gradients

    # adaptive accumulator: sum of (g x)^2 like VW's per-example AdaGrad
    g2 = scatter(state.g2, gx * gx, "add") if cfg.adaptive else state.g2
    bias_g2 = state.bias_g2 + (g * g).sum() if cfg.adaptive else state.bias_g2

    # normalized: track running per-feature scale max|x|
    scale = (scatter(state.scale, jnp.abs(values), "max")
             if cfg.normalized else state.scale)

    t = state.t + wt.sum()
    step, bias_step = _step_updates(
        cfg, pred, y, wt, values, gx, g_raw, g,
        gather(g2) if cfg.adaptive else None,
        gather(scale) if cfg.normalized else None, bias_g2, t)

    w = _regularize(cfg, scatter(state.w, -step, "add"))
    bias = state.bias - bias_step if cfg.use_constant else state.bias

    new_state = VWState(w=w, g2=g2, scale=scale, bias=bias,
                        bias_g2=bias_g2, t=t)
    denom = jnp.maximum(wt.sum(), 1e-9)
    return new_state, (lv * wt).sum() / denom


# -------------------------------------------------------- fused packed path

def _packed_layout(cfg: VWConfig):
    """Row layout of the fused [R, 2^b] table: w is always row 0; g2 and
    scale are packed only when their mode is on (R = 3 with
    adaptive+normalized, 2 with one of them, 1 for plain SGD).

    Returns (row_g2, row_scale, nrows) with None for absent rows."""
    row_g2 = 1 if cfg.adaptive else None
    row_scale = ((2 if cfg.adaptive else 1) if cfg.normalized else None)
    nrows = 1 + (row_g2 is not None) + (row_scale is not None)
    return row_g2, row_scale, nrows


def pack_state(cfg: VWConfig, state: VWState):
    """VWState -> the fused step's carry (packed[R,F], bias, bias_g2, t)."""
    row_g2, row_scale, _ = _packed_layout(cfg)
    parts = [state.w]
    if row_g2 is not None:
        parts.append(state.g2)
    if row_scale is not None:
        parts.append(state.scale)
    return (jnp.stack(parts, axis=0), state.bias, state.bias_g2, state.t)


def unpack_state(cfg: VWConfig, carry, template: VWState) -> VWState:
    """Fused carry -> VWState. Tables the fused layout does not carry
    (g2 with adaptive off, scale with normalized off) pass through from
    `template` untouched — exactly what the unpacked step does to them."""
    packed, bias, bias_g2, t = carry
    row_g2, row_scale, _ = _packed_layout(cfg)
    return VWState(
        w=packed[0],
        g2=packed[row_g2] if row_g2 is not None else template.g2,
        scale=packed[row_scale] if row_scale is not None else template.scale,
        bias=bias, bias_g2=bias_g2, t=t)


def _fused_minibatch_step(cfg: VWConfig, carry, batch):
    """One SGD minibatch against the packed [R, 2^b] table: ONE gather,
    ONE scatter, regardless of how many of (w, g2, scale) are live.

    The unpacked step re-gathers g2/scale right after scattering them (the
    per-weight rates want post-update values). Here those reads are
    emulated locally: one argsort of the step's indices yields
    duplicate-index runs, and segment reductions over the runs reproduce
    gather-after-scatter exactly — `add` runs for g2 (same totals as the
    scatter, reassociated), `max` runs for scale (bit-exact; max is
    insensitive to order). The scale table's max-update is then fused into
    the single scatter-ADD as a first-occurrence delta per distinct index:
    table + max(batch_max - table, 0) == max(table, batch_max) up to one
    subtract/add rounding (<= 1 ulp; both operands are >= 0).

    Composes with the shared-index pre-reduction: in shared mode the batch
    axis is pre-reduced per op (sum for w/g2, max for scale) BEFORE the
    duplicate-run pass, so the scatter stays [k]-wide."""
    packed, bias0, bias_g2, t = carry
    indices, values, y, wt = batch           # [B,k], [B,k], [B], [B]
    row_g2, row_scale, _ = _packed_layout(cfg)
    bsz, k = values.shape

    if cfg.shared_indices:
        fi = indices[0]                      # [k] — every real row identical
        pg = packed[:, fi]                   # [R, k]   THE one gather
        red_add = lambda u: u.sum(axis=0)    # batch pre-reduction per op
        red_max = lambda u: u.max(axis=0)
        view = lambda v: v[None, :]          # flat [k] -> broadcast [1, k]
        flat_row = lambda r: pg[r]
    else:
        fi = indices.reshape(-1)             # [B*k]
        pg = packed[:, indices]              # [R, B, k] THE one gather
        red_add = lambda u: u.reshape(-1)
        red_max = red_add
        view = lambda v: v.reshape(bsz, k)
        flat_row = lambda r: pg[r].reshape(-1)

    n_flat = fi.shape[0]
    order = jnp.argsort(fi)
    fs = fi[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), fs[1:] != fs[:-1]])   # run starts
    seg = jnp.cumsum(first) - 1                      # sorted run ids
    inv = jnp.zeros((n_flat,), order.dtype).at[order].set(
        jnp.arange(n_flat, dtype=order.dtype))       # unsort permutation

    pred = (view(flat_row(0)) * values).sum(axis=-1) + bias0
    lv, g_raw = _loss_and_grad(cfg.loss, pred, y)
    g = g_raw * wt
    gx = g[:, None] * values

    upd = [None] * len(pg)                           # the one scatter's rows
    g2_view = scale_view = None
    if cfg.adaptive:
        u2 = red_add(gx * gx)                        # [n_flat]
        tot = jax.ops.segment_sum(u2[order], seg, num_segments=n_flat,
                                  indices_are_sorted=True)
        # gather-after-scatter emulation: old value + total update landing
        # on the same index anywhere in the batch
        g2_view = view(flat_row(row_g2) + tot[seg][inv])
        bias_g2 = bias_g2 + (g * g).sum()
        upd[row_g2] = u2                             # scatter-add sums dups
    if cfg.normalized:
        m = red_max(jnp.abs(values))                 # [n_flat]
        sg = flat_row(row_scale)
        mx = jax.ops.segment_max(m[order], seg, num_segments=n_flat,
                                 indices_are_sorted=True)
        run_max = mx[seg]                            # sorted order
        scale_view = view(jnp.maximum(sg, run_max[inv]))
        # max fused into the add-scatter: the positive delta lands ONCE per
        # distinct index (at its sorted run's first slot); every other
        # duplicate contributes 0, so the sum reproduces the max
        delta = jnp.where(first, jnp.maximum(run_max - sg[order], 0.0), 0.0)
        upd[row_scale] = delta[inv]

    t = t + wt.sum()
    step, bias_step = _step_updates(cfg, pred, y, wt, values, gx, g_raw, g,
                                    g2_view, scale_view, bias_g2, t)
    upd[0] = red_add(-step)
    packed = packed.at[:, fi].add(jnp.stack(upd, axis=0))  # THE one scatter
    if cfg.l1 > 0.0 or cfg.l2 > 0.0:
        packed = packed.at[0].set(_regularize(cfg, packed[0]))
    bias = bias0 - bias_step if cfg.use_constant else bias0

    denom = jnp.maximum(wt.sum(), 1e-9)
    return (packed, bias, bias_g2, t), (lv * wt).sum() / denom


def make_step_fn(cfg: VWConfig):
    """The single-minibatch step for cfg's table layout, as
    step(carry, (indices, values, labels, weights)) -> (carry, loss).
    The carry is pack_state's tuple when cfg.fused, a VWState otherwise
    (pair with pack_state/unpack_state)."""
    return partial(_fused_minibatch_step if cfg.fused else _minibatch_step,
                   cfg)


def resolve_auto_fused(adaptive: bool, normalized: bool,
                       backend: Optional[str] = None) -> bool:
    """fusedTables='auto' rule, pinned by the measured batch-size ladder
    (scripts/measure_vw_throughput.py, docs/PERF.md, docs/VW.md).

    Packing only pays where per-kernel scatter dispatch dominates the
    step — the accelerator backends. On CPU the measured ladder shows the
    OPPOSITE: XLA lowers each scatter to a cheap serial loop while the
    fused path's duplicate-run sort is real work, so unpacked runs
    1.4-4x faster across every rung (2026-08 CPU ladder). Hence:

    - cpu backend: never pack (auto == off);
    - other backends: pack whenever the step updates >= 2 tables
      (adaptive or normalized on). Plain SGD runs one table either way,
      so packing would only add stack/slice overhead.
    """
    if backend is None:
        backend = jax.default_backend()
    return (bool(adaptive) or bool(normalized)) and backend != "cpu"


def _cross_shard_reduce(cfg: VWConfig, carry):
    """Per-pass allreduce over cfg.axis_name — the spanning-tree
    equivalent (vw/VowpalWabbitBase.scala:401-429). Handles both carry
    layouts: VWState (unpacked) and the fused (packed, bias, bias_g2, t)
    tuple, where every packed row pmean-averages EXCEPT the scale row,
    which is a running max and must pmax like the unpacked path."""
    ax = cfg.axis_name
    if cfg.fused:
        packed, bias, bias_g2, t = carry
        _, row_scale, _ = _packed_layout(cfg)
        mean = jax.lax.pmean(packed, ax)
        if row_scale is not None:
            mean = mean.at[row_scale].set(
                jax.lax.pmax(packed[row_scale], ax))
        return (mean, jax.lax.pmean(bias, ax),
                jax.lax.pmean(bias_g2, ax), jax.lax.psum(t, ax))
    return VWState(
        w=jax.lax.pmean(carry.w, ax),
        g2=jax.lax.pmean(carry.g2, ax),
        scale=jax.lax.pmax(carry.scale, ax),
        bias=jax.lax.pmean(carry.bias, ax),
        bias_g2=jax.lax.pmean(carry.bias_g2, ax),
        t=jax.lax.psum(carry.t, ax),
    )


def make_train_fn(cfg: VWConfig):
    """Build the jitted multi-pass trainer.

    fn(indices[n,k], values[n,k], labels[n], weights[n], state) ->
    (VWState, pass_losses[num_passes]). n must be a multiple of cfg.minibatch
    (pad rows with weight 0). When cfg.axis_name is set the function is meant
    to run inside shard_map; weights are pmean-averaged across shards after
    every pass — the spanning-tree allreduce equivalent
    (vw/VowpalWabbitBase.scala:401-429). With cfg.fused the scan carries the
    packed [R, 2^b] table (pack once before the first pass, unpack once at
    the end) so every minibatch runs the one-gather/one-scatter step."""
    step = make_step_fn(cfg)

    def one_pass(carry, batches):
        carry, losses = jax.lax.scan(step, carry, batches)
        if cfg.axis_name is not None:
            carry = _cross_shard_reduce(cfg, carry)
            losses = jax.lax.pmean(losses, cfg.axis_name)
        return carry, losses.mean()

    def train(indices, values, labels, weights, state):
        n, k = indices.shape
        b = cfg.minibatch
        nb = n // b
        batches = (
            indices.reshape(nb, b, k),
            values.reshape(nb, b, k),
            labels.reshape(nb, b),
            weights.reshape(nb, b),
        )
        carry = pack_state(cfg, state) if cfg.fused else state
        pass_losses = []
        for _ in range(cfg.num_passes):
            carry, mean_loss = one_pass(carry, batches)
            pass_losses.append(mean_loss)
        if cfg.fused:
            carry = unpack_state(cfg, carry, state)
        return carry, jnp.stack(pass_losses)

    return train


def pad_examples(indices: np.ndarray, values: np.ndarray, labels: np.ndarray,
                 weights: np.ndarray, multiple: int):
    """Pad rows to a multiple of the minibatch size with zero-weight examples."""
    n = indices.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return indices, values, labels, weights
    pad = lambda a, fill: np.concatenate(
        [a, np.full((rem,) + a.shape[1:], fill, a.dtype)], axis=0)
    return (pad(indices, 0), pad(values, 0.0),
            pad(labels, 1.0 if labels.dtype.kind == "f" else 0),
            pad(weights, 0.0))


# ------------------------------------------------- durable state (ISSUE 19)

#: VWState fields in canonical digest/serialization order — a NamedTuple's
#: field order IS this order, pinned here so a reordering refactor cannot
#: silently change every stored digest
STATE_FIELDS = ("w", "g2", "scale", "bias", "bias_g2", "t")


def state_to_bytes(state: VWState) -> bytes:
    """Serialize a VWState to portable npz bytes (the online loop's
    checkpoint payload — host numpy, device-count independent)."""
    import io
    buf = io.BytesIO()
    np.savez(buf, **{f: np.asarray(getattr(state, f))
                     for f in STATE_FIELDS})
    return buf.getvalue()


def state_from_bytes(data: bytes) -> VWState:
    """Inverse of `state_to_bytes`; arrays land back on the default
    device lazily at the first step that consumes them."""
    import io
    with np.load(io.BytesIO(data)) as z:
        return VWState(**{f: jnp.asarray(z[f]) for f in STATE_FIELDS})


def state_digest(state: VWState) -> str:
    """sha256 over the canonical field bytes — the exactly-once proof's
    currency: two learners that applied the same rewards in the same
    minibatch grouping have equal digests (bit-identical float32 state)."""
    import hashlib
    h = hashlib.sha256()
    for f in STATE_FIELDS:
        a = np.ascontiguousarray(np.asarray(getattr(state, f)))
        h.update(f.encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return "sha256:" + h.hexdigest()
