"""jit-compiled sparse linear SGD — the VowpalWabbit C++ core, the TPU way.

Reference behavior being replaced (vw/VowpalWabbitBase.scala:235-341 +
`vw-jni 8.8.1` C++): per-example online updates with adaptive (AdaGrad),
normalized (per-feature scale), and importance-invariant steps; L1/L2
regularization; multi-pass over a cache file; per-pass spanning-tree allreduce
of weights across workers (trainInternalDistributed, :401-429).

TPU design: examples are packed into fixed-width sparse batches
(models/vw/sparse.py) and a `lax.scan` walks minibatches, so one XLA program
runs the whole pass with static shapes. Exact per-example ordering is traded
for minibatch equivalence (SURVEY.md §7: "minibatched SGD with equivalence
tolerances rather than bit parity"). The spanning-tree allreduce becomes a
`lax.pmean` over the mesh data axis at the end of every pass.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class VWConfig(NamedTuple):
    num_features: int
    loss: str = "squared"          # "squared" | "logistic"
    learning_rate: float = 0.5     # VW -l default
    power_t: float = 0.5           # VW --power_t default
    initial_t: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    adaptive: bool = True          # VW default: adaptive+normalized+invariant
    normalized: bool = True
    invariant: bool = True
    num_passes: int = 1
    minibatch: int = 256
    use_constant: bool = True      # VW constant feature (--noconstant off)
    axis_name: Optional[str] = None  # set => per-pass pmean over this mesh axis
    # row-invariant index layout (dense feature columns, incl. their
    # interactions): every real row carries the SAME index vector, so the
    # per-step [B, k] scatter-add/max — whose indices then collide
    # TOTALLY, the TPU sort-based scatter's worst case — pre-reduces over
    # the batch axis to a [k] scatter with identical totals (addition
    # commutes; gather-after-scatter sees the same sums; padded rows
    # carry value 0 and contribute nothing either way). Set by the
    # estimator after checking the actual arrays; measured ~4 ms -> sub-ms
    # per minibatch step on chip at 2^18 features.
    shared_indices: bool = False


class VWState(NamedTuple):
    """Learner state as a pytree (the model 'weights file')."""
    w: jnp.ndarray        # [F] feature weights
    g2: jnp.ndarray       # [F] AdaGrad sum of squared gradients
    scale: jnp.ndarray    # [F] per-feature max |x| seen (normalized updates)
    bias: jnp.ndarray     # [] constant term (VW's constant feature)
    bias_g2: jnp.ndarray  # []
    t: jnp.ndarray        # [] example counter (importance-weighted)


def init_state(num_features: int) -> VWState:
    f = num_features
    return VWState(
        w=jnp.zeros((f,), jnp.float32),
        g2=jnp.zeros((f,), jnp.float32),
        scale=jnp.zeros((f,), jnp.float32),
        bias=jnp.zeros((), jnp.float32),
        bias_g2=jnp.zeros((), jnp.float32),
        t=jnp.zeros((), jnp.float32),
    )


def _loss_and_grad(loss: str, pred, y):
    """Returns (per-row loss, dloss/dpred). Labels: squared = real values,
    logistic = {-1,+1} (VW convention)."""
    if loss == "logistic":
        margin = y * pred
        lv = jnp.logaddexp(0.0, -margin)
        g = -y * jax.nn.sigmoid(-margin)
        return lv, g
    diff = pred - y
    return 0.5 * diff * diff, diff


def predict_batch(state: VWState, indices, values):
    """Margin for a padded sparse batch: sum_k w[idx]*val + bias."""
    return (state.w[indices] * values).sum(axis=-1) + state.bias


def _invariant_delta(loss: str, pred, y, xbar, h):
    """Karampatziakis-Langford importance-weight-aware update: the exact
    change in prediction after following the gradient flow
    p'(tau) = -xbar * loss'(p) for time h (the importance weight), where
    xbar = sum_i r_i x_i^2 is the example's squared norm under the per-weight
    learning rates. Guarantees the update never overshoots the label.

    squared: p(h) = y + (p0-y) e^{-xbar h}  =>  dp = (y-p0)(1-e^{-xbar h}).
    logistic (y in {-1,+1}): with m = y p, m + e^m is conserved up to xbar*h:
    m1 + e^{m1} = m0 + e^{m0} + xbar h — solved by Newton (convex, explicit
    Euler start). Reference: VW gd.cc invariant updates (the reference forwards
    --invariant to C++, vw/VowpalWabbitBase.scala:139-169)."""
    if loss == "squared":
        return (y - pred) * (1.0 - jnp.exp(-xbar * h))
    # logistic
    m0 = jnp.clip(y * pred, -30.0, 30.0)
    em0 = jnp.exp(m0)
    target = xbar * h
    dm = target * jax.nn.sigmoid(-m0)  # explicit-Euler start (underestimate)
    for _ in range(3):                 # Newton on dm + e^{m0}(e^{dm}-1) = t
        e = em0 * (jnp.exp(jnp.clip(dm, -30.0, 30.0)) - 1.0)
        phi = dm + e - target
        dphi = 1.0 + em0 * jnp.exp(jnp.clip(dm, -30.0, 30.0))
        dm = dm - phi / dphi
    return y * dm


def _minibatch_step(cfg: VWConfig, state: VWState, batch):
    indices, values, y, wt = batch   # [B,k], [B,k], [B], [B]
    # shared-index mode (cfg.shared_indices): every real row carries the
    # index vector of row 0, so gathers read [k] once and scatters
    # pre-reduce over the batch axis — same totals, no total-collision
    # scatter. sidx stays None on the general path.
    sidx = indices[0] if cfg.shared_indices else None

    def gather(tab):
        """[B, k] per-row view of a [F] table on either path (shared mode
        reads the [k] slots once and broadcasts)."""
        return tab[sidx][None, :] if cfg.shared_indices else tab[indices]

    def scatter(tab, upd, op):
        """Accumulate a [B, k] update into a [F] table; shared mode
        pre-reduces the batch axis (sum for add, max for max) so the
        scatter is [k]-wide with no total-collision worst case."""
        if cfg.shared_indices:
            red = upd.sum(axis=0) if op == "add" else upd.max(axis=0)
            at = tab.at[sidx]
            return at.add(red) if op == "add" else at.max(red)
        at = tab.at[indices]
        return at.add(upd) if op == "add" else at.max(upd)

    pred = (gather(state.w) * values).sum(axis=-1) + state.bias
    lv, g_raw = _loss_and_grad(cfg.loss, pred, y)
    g = g_raw * wt                               # importance weight
    gx = g[:, None] * values                     # [B,k] per-weight gradients

    # adaptive accumulator: sum of (g x)^2 like VW's per-example AdaGrad
    g2 = scatter(state.g2, gx * gx, "add") if cfg.adaptive else state.g2
    bias_g2 = state.bias_g2 + (g * g).sum() if cfg.adaptive else state.bias_g2

    # normalized: track running per-feature scale max|x|
    scale = (scatter(state.scale, jnp.abs(values), "max")
             if cfg.normalized else state.scale)

    t = state.t + wt.sum()
    if cfg.adaptive:
        rate = cfg.learning_rate / (jnp.sqrt(gather(g2)) + 1e-6)
        bias_rate = cfg.learning_rate / (jnp.sqrt(bias_g2) + 1e-6)
    else:
        # decayed global rate: eta * (t0+1 / (t0+t))^power_t
        r = cfg.learning_rate * jnp.power(
            (cfg.initial_t + 1.0) / (cfg.initial_t + t + 1.0), cfg.power_t)
        rate = jnp.broadcast_to(r, values.shape)
        bias_rate = r
    if cfg.normalized:
        rate = rate / jnp.maximum(gather(scale), 1e-6)

    if cfg.invariant:
        # exact importance-weight-aware update: compute the closed-form
        # prediction change dp and distribute it over the weights so the
        # example's prediction moves by exactly dp (never past the label).
        # The shared bias moves by the minibatch MEAN of per-example bias
        # steps, so its contribution to each example's xbar is bias_rate/B —
        # batch-total prediction change then matches batch-total dp exactly.
        xbar = (rate * values * values).sum(axis=-1)  # [B]
        if cfg.use_constant:
            xbar = xbar + bias_rate / values.shape[0]
        dp = _invariant_delta(cfg.loss, pred, y, xbar, wt)
        # dp/xbar is the per-unit step; as xbar->0 it limits to -g*h
        unit = jnp.where(xbar > 1e-12, dp / xbar, -g_raw * wt)
        step = -(rate * values) * unit[:, None]
        bias_step = -(bias_rate * unit).mean()
    else:
        step = rate * gx
        bias_step = bias_rate * g.mean()

    w = scatter(state.w, -step, "add")
    bias = state.bias - bias_step if cfg.use_constant else state.bias

    # L2 shrink + L1 truncated gradient, vectorized over the whole weight table
    if cfg.l2 > 0.0:
        w = w * (1.0 - cfg.learning_rate * cfg.l2)
    if cfg.l1 > 0.0:
        thresh = cfg.learning_rate * cfg.l1
        w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - thresh, 0.0)

    new_state = VWState(w=w, g2=g2, scale=scale, bias=bias,
                        bias_g2=bias_g2, t=t)
    denom = jnp.maximum(wt.sum(), 1e-9)
    return new_state, (lv * wt).sum() / denom


def make_train_fn(cfg: VWConfig):
    """Build the jitted multi-pass trainer.

    fn(indices[n,k], values[n,k], labels[n], weights[n], state) ->
    (VWState, pass_losses[num_passes]). n must be a multiple of cfg.minibatch
    (pad rows with weight 0). When cfg.axis_name is set the function is meant
    to run inside shard_map; weights are pmean-averaged across shards after
    every pass — the spanning-tree allreduce equivalent
    (vw/VowpalWabbitBase.scala:401-429)."""

    def one_pass(state, batches):
        state, losses = jax.lax.scan(
            partial(_minibatch_step, cfg), state, batches)
        if cfg.axis_name is not None:
            state = VWState(
                w=jax.lax.pmean(state.w, cfg.axis_name),
                g2=jax.lax.pmean(state.g2, cfg.axis_name),
                scale=jax.lax.pmax(state.scale, cfg.axis_name),
                bias=jax.lax.pmean(state.bias, cfg.axis_name),
                bias_g2=jax.lax.pmean(state.bias_g2, cfg.axis_name),
                t=jax.lax.psum(state.t, cfg.axis_name),
            )
            losses = jax.lax.pmean(losses, cfg.axis_name)
        return state, losses.mean()

    def train(indices, values, labels, weights, state):
        n, k = indices.shape
        b = cfg.minibatch
        nb = n // b
        batches = (
            indices.reshape(nb, b, k),
            values.reshape(nb, b, k),
            labels.reshape(nb, b),
            weights.reshape(nb, b),
        )
        pass_losses = []
        for _ in range(cfg.num_passes):
            state, mean_loss = one_pass(state, batches)
            pass_losses.append(mean_loss)
        return state, jnp.stack(pass_losses)

    return train


def pad_examples(indices: np.ndarray, values: np.ndarray, labels: np.ndarray,
                 weights: np.ndarray, multiple: int):
    """Pad rows to a multiple of the minibatch size with zero-weight examples."""
    n = indices.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return indices, values, labels, weights
    pad = lambda a, fill: np.concatenate(
        [a, np.full((rem,) + a.shape[1:], fill, a.dtype)], axis=0)
    return (pad(indices, 0), pad(values, 0.0),
            pad(labels, 1.0 if labels.dtype.kind == "f" else 0),
            pad(weights, 0.0))
