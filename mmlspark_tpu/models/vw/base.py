"""VowpalWabbitBase — shared estimator surface for the VW-equivalent learners.

Reference: vw/VowpalWabbitBase.scala:71-521 — typed params mirrored into a CLI
arg string via `appendParamIfNotThere` (:139-169), per-partition native training
with `TrainContext`/`TrainingStats` diagnostics (:27-49, 268-303), multi-pass via
cache file (:222-227), distributed weight averaging through the driver spanning
tree (:401-429), final model from partition 0 (:355).

TPU design: the CLI string survives only as a compatibility surface
(`passThroughArgs`, parsed into the same typed params); training is one jitted
multi-pass program (models/vw/sgd.py), sharded over the mesh data axis with
per-pass `pmean` instead of the spanning tree. There is no "model from partition
0": after the final pmean every shard holds the averaged model.
"""

from __future__ import annotations

import shlex
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...compile import cache as compilecache
from ...core import params as _p
from ...core.dataframe import DataFrame
from ...core.pipeline import Estimator, Model
from ...observability import bridge as obsbridge
from ...parallel import mesh as meshlib
from .sgd import (VWConfig, VWState, init_state, make_train_fn, pad_examples,
                  resolve_auto_fused)
from .sparse import SparseFeatures


class VowpalWabbitParamsBase(_p.HasFeaturesCol, _p.HasLabelCol,
                             _p.HasWeightCol):
    passThroughArgs = _p.Param(
        "passThroughArgs", "VW-style CLI arg string; parsed flags override "
        "typed params (appendParamIfNotThere semantics reversed: the string "
        "wins, as in the reference where typed params are only appended if "
        "absent from args)", "")
    learningRate = _p.Param("learningRate", "SGD learning rate (-l)", 0.5, float)
    powerT = _p.Param("powerT", "t decay exponent (--power_t)", 0.5, float)
    initialT = _p.Param("initialT", "initial t (--initial_t)", 0.0, float)
    l1 = _p.Param("l1", "L1 regularization (--l1)", 0.0, float)
    l2 = _p.Param("l2", "L2 regularization (--l2)", 0.0, float)
    numPasses = _p.Param("numPasses", "passes over the data (--passes)", 1, int)
    numBits = _p.Param("numBits", "log2 weight-table size (-b)", 18, int)
    adaptive = _p.Param("adaptive", "AdaGrad per-weight rates (--adaptive)",
                        True, bool)
    normalized = _p.Param("normalized", "per-feature scale normalization",
                          True, bool)
    invariant = _p.Param("invariant", "importance-invariant safeguarding",
                         True, bool)
    minibatchSize = _p.Param(
        "minibatchSize", "examples per fused SGD step (TPU-specific: the "
        "online loop is minibatched for static shapes)", 256, int)
    numTasks = _p.Param(
        "numTasks", "data-parallel shards over the device mesh (reference: "
        "Spark task count, ClusterUtil); 0 (default) = auto — all local "
        "devices when the dataset is large enough to amortize sharding "
        "(>= 2^17 rows; per-pass pmean weight averaging is the "
        "reference's spanning-tree semantics, not bit-identical to the "
        "serial SGD stream), one device below that", 0, int)
    useBarrierExecutionMode = _p.Param(
        "useBarrierExecutionMode", "accepted for API parity; SPMD launch is "
        "inherently gang-scheduled so this is a no-op", False, bool)
    fusedTables = _p.Param(
        "fusedTables",
        "pack the w/g2/scale tables into one [R, 2^b] table so each SGD "
        "step issues ONE gather and ONE scatter instead of up to three of "
        "each (auto | on | off). auto packs whenever adaptive or "
        "normalized needs a second table — the rule pinned by the "
        "measured ladder (scripts/measure_vw_throughput.py, docs/VW.md)",
        "auto")
    metricsEvery = _p.Param(
        "metricsEvery",
        "online-ring telemetry cadence: fetch the loss and publish "
        "vw_examples_per_s / vw_step_seconds every N retired steps — the "
        "ring's ONLY host syncs outside commit points "
        "(models/vw/online.py)", 10, int)

    interactions = _p.Param(
        "interactions", "namespace interaction terms as VW -q pairs (e.g. "
        "['ab']); namespaces = featuresCol/additionalFeatures column names, "
        "matched by first letter (VowpalWabbitBase.scala interactions param)",
        None)
    additionalFeatures = _p.Param(
        "additionalFeatures", "extra hashed-feature columns, each its own "
        "namespace (HasAdditionalFeatures in the reference)", None)
    # NOTE: no hashSeed param here (reference VowpalWabbitBase.scala:171-176
    # has one because C++ hashes inside the learner) — hashing happens in
    # VowpalWabbitFeaturizer(seed=...); a learner-side seed would be a no-op
    ignoreNamespaces = _p.Param(
        "ignoreNamespaces", "namespaces to drop, by first letter "
        "(--ignore)", "")

    # ------------------------------------------------------------ arg string
    _ARG_MAP = {
        "-l": ("learningRate", float), "--learning_rate": ("learningRate", float),
        "--power_t": ("powerT", float), "--initial_t": ("initialT", float),
        "--l1": ("l1", float), "--l2": ("l2", float),
        "--passes": ("numPasses", int), "-b": ("numBits", int),
        "--bit_precision": ("numBits", int),
    }
    _FLAG_MAP = {
        "--adaptive": ("adaptive", True), "--normalized": ("normalized", True),
        "--invariant": ("invariant", True),
        "--sgd": ("adaptive", False),  # plain sgd disables ada/norm/inv
        "--noconstant": ("useConstant", False),
    }
    # display/IO flags with no semantic effect in this engine — accepted
    _NOOP_FLAGS = {"--quiet", "--no_stdin", "--holdout_off"}
    _SUPPORTED_LOSSES = {"squared", "logistic", "classic"}

    def _effective_params(self) -> Dict[str, object]:
        """Typed params overridden by flags parsed from passThroughArgs.

        Every token is either honored or rejected with ValueError — the
        reference forwards the full CLI string to C++ where every flag has
        effect (VowpalWabbitBase.scala:139-169, :496-508); silently ignoring
        flags would be silent semantic divergence, which is worse than an
        error (round-1 verdict Missing #5)."""
        out: Dict[str, object] = {
            name: self.get(name)
            for name in ("learningRate", "powerT", "initialT", "l1", "l2",
                         "numPasses", "numBits", "adaptive", "normalized",
                         "invariant")}
        out["useConstant"] = True
        out["loss"] = None  # None = subclass default
        out["link"] = None  # None = subclass default
        out["interactions"] = list(self.get("interactions") or [])
        out["ignore"] = list(self.get("ignoreNamespaces") or "")
        toks = shlex.split(self.get("passThroughArgs") or "")
        i = 0
        while i < len(toks):
            tok = toks[i]
            if tok in self._ARG_MAP:
                name, conv = self._ARG_MAP[tok]
                if i + 1 >= len(toks):
                    raise ValueError(f"VW argument {tok} expects a value")
                out[name] = conv(toks[i + 1])
                i += 2
            elif tok in self._FLAG_MAP:
                name, value = self._FLAG_MAP[tok]
                if tok == "--sgd":
                    out["adaptive"] = out["normalized"] = out["invariant"] = False
                else:
                    out[name] = value
                i += 1
            elif tok in self._NOOP_FLAGS:
                i += 1
            elif tok in ("-q", "--quadratic", "--interactions"):
                if i + 1 >= len(toks):
                    raise ValueError(f"VW argument {tok} expects a value")
                out["interactions"].append(toks[i + 1])
                i += 2
            elif tok == "--ignore":
                if i + 1 >= len(toks):
                    raise ValueError("--ignore expects a namespace letter")
                out["ignore"].append(toks[i + 1][0])
                i += 2
            elif tok == "--loss_function":
                if i + 1 >= len(toks):
                    raise ValueError("--loss_function expects a value")
                loss = toks[i + 1]
                if loss not in self._SUPPORTED_LOSSES:
                    raise ValueError(
                        f"unsupported --loss_function {loss!r}: this engine "
                        f"implements {sorted(self._SUPPORTED_LOSSES)}")
                if loss == "classic":  # squared without invariant safeguards
                    out["loss"] = "squared"
                    out["invariant"] = False
                else:
                    out["loss"] = loss
                i += 2
            elif tok == "--link":
                if i + 1 >= len(toks):
                    raise ValueError("--link expects a value")
                if toks[i + 1] not in ("identity", "logistic"):
                    raise ValueError(
                        f"unsupported --link {toks[i + 1]!r}")
                out["link"] = toks[i + 1]
                i += 2
            elif tok == "--hash_seed":
                raise ValueError(
                    "--hash_seed has no effect here: features are hashed "
                    "upstream of the learner — set "
                    "VowpalWabbitFeaturizer(seed=...) instead (rejected "
                    "loudly rather than silently ignored)")
            else:
                raise ValueError(
                    f"unsupported VW argument {tok!r}: this TPU engine "
                    f"honors {sorted(set(self._ARG_MAP) | set(self._FLAG_MAP) | self._NOOP_FLAGS | {'-q', '--quadratic', '--interactions', '--ignore', '--loss_function', '--link'})}; "
                    "unrecognized flags are rejected instead of silently "
                    "ignored (VowpalWabbitBase.scala:139-169 forwards every "
                    "flag to C++ where it has effect)")
        return out

    def _resolve_fused(self, adaptive: bool, normalized: bool) -> bool:
        """Resolve fusedTables (auto/on/off) to the concrete step layout
        and publish the decision (vw_fused_tables_total) so the fleet's
        resolved layouts are scrapeable."""
        mode = str(self.get("fusedTables")).lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"fusedTables must be 'auto', 'on' or 'off', got "
                f"{self.get('fusedTables')!r}")
        fused = (resolve_auto_fused(adaptive, normalized) if mode == "auto"
                 else mode == "on")
        obsbridge.publish_vw_fused_decision(mode, fused)
        return fused


def _masked_features(col: np.ndarray, num_bits: int) -> SparseFeatures:
    """Extract a sparse batch whose indices are masked into [0, 2^num_bits):
    the weight table size is ALWAYS exactly 2^numBits, so a featurizer hashed
    with more bits than the learner folds down deterministically instead of
    relying on gather clamping."""
    nf = 1 << int(num_bits)
    feats = SparseFeatures.from_column(col, num_features=nf)
    if feats.num_features > nf:  # from_column grows to max observed index + 1
        feats = SparseFeatures(feats.indices % nf, feats.values, nf)
    return feats


def _interact_pair(i1, v1, i2, v2, mask: int):
    """Vectorized outer-product interaction of two namespaces: FNV-1a-style
    index combine (VW interact()) + value product. Padding slots carry value
    0, so their products stay 0."""
    ci = ((i1[:, :, None] * np.int64(0x01000193)) ^ i2[:, None, :]) & mask
    cv = v1[:, :, None] * v2[:, None, :]
    n = ci.shape[0]
    return ci.reshape(n, -1), cv.reshape(n, -1)


def _interact_self(i1, v1, mask: int):
    """Self-interaction of a namespace in VW 'combinations' mode: each
    unordered feature pair (p <= q) once — not the full permutation product."""
    k = i1.shape[1]
    p, q = np.triu_indices(k)
    ci = ((i1[:, p] * np.int64(0x01000193)) ^ i1[:, q]) & mask
    cv = v1[:, p] * v1[:, q]
    return ci, cv


def _assemble_features(df: DataFrame, features_col: str, additional,
                       interactions, ignore, num_bits: int) -> SparseFeatures:
    """Build per-example sparse features from namespace columns plus `-q`
    interaction terms — the example-construction work the reference does in
    C++ from the CLI string (VowpalWabbitBase.scala:235-266; interactions
    applied natively from `-q`/--interactions args).

    Namespaces = featuresCol + additionalFeatures columns, matched by FIRST
    LETTER of the column name (VW semantics). --ignore drops namespaces before
    interaction expansion."""
    nf = 1 << int(num_bits)
    mask = nf - 1
    names = [features_col] + list(additional or [])
    ignored = {c for c in names if c and c[0] in set(ignore or [])}
    names = [c for c in names if c not in ignored]
    if not names:
        raise ValueError("--ignore dropped every namespace")
    cols = {c: _masked_features(df[c], num_bits) for c in names}

    idx_parts = [cols[c].indices.astype(np.int64) for c in names]
    val_parts = [cols[c].values.astype(np.float32) for c in names]
    for spec in interactions or []:
        letters = [ch for ch in spec if not ch.isspace()]
        if len(letters) < 2:
            raise ValueError(f"interaction spec {spec!r} needs >= 2 "
                             "namespace letters")
        groups = []
        for ch in letters:
            matching = [c for c in names if c.startswith(ch)]
            if not matching:
                raise ValueError(
                    f"interaction {spec!r}: no namespace column starts with "
                    f"{ch!r} (namespaces: {names}); name your feature "
                    "columns so first letters match the -q spec")
            groups.append(matching)
        # VW default is "combinations", not permutations: for a namespace
        # interacted with itself (-q aa) each unordered feature pair appears
        # once (i <= j), and duplicate column pairs collapse to one
        if len(letters) == 2 and groups[0] == groups[1]:
            from itertools import combinations_with_replacement
            combos = list(combinations_with_replacement(groups[0], 2))
        else:
            from itertools import product
            combos = list(product(*groups))
        for combo in combos:
            if len(combo) == 2 and combo[0] == combo[1]:
                i_acc, v_acc = _interact_self(
                    cols[combo[0]].indices.astype(np.int64),
                    cols[combo[0]].values.astype(np.float32), mask)
            else:
                i_acc = cols[combo[0]].indices.astype(np.int64)
                v_acc = cols[combo[0]].values.astype(np.float32)
                for c in combo[1:]:
                    i_acc, v_acc = _interact_pair(
                        i_acc, v_acc, cols[c].indices.astype(np.int64),
                        cols[c].values.astype(np.float32), mask)
            idx_parts.append(i_acc)
            val_parts.append(v_acc)
    indices = np.concatenate(idx_parts, axis=1)
    values = np.concatenate(val_parts, axis=1)
    return SparseFeatures(indices.astype(np.int32), values, nf)


def _score_batch_impl(w, bias, indices, values):
    """Batched margin: sum_k w[idx]*val + bias (weights are traced args,
    not baked-in constants)."""
    return (w[indices] * values).sum(axis=-1) + bias


def _score_batch(w, bias, indices, values):
    """Serving-side margin, acquired via the shared cached_jit registry
    (compile/): cached across transform calls AND counted in cache_stats."""
    return compilecache.cached_jit(
        _score_batch_impl, key="vw_score",
        name="vw_score")(w, bias, indices, values)


class VowpalWabbitBase(VowpalWabbitParamsBase, Estimator):
    """Shared fit(): extract sparse batch -> jit multi-pass SGD -> model."""

    _loss = "squared"  # subclass override

    initialModel = _p.Param(
        "initialModel",
        "warm-start from a fitted VowpalWabbit model (its weight table seeds "
        "training; numBits must match) — the reference's initialModel model "
        "bytes (VowpalWabbitBase.scala)", None, complex=True)
    performanceStatistics = _p.Param(
        "performanceStatistics",
        "compat: per-partition perf stats are always collected and exposed "
        "via the model's get_performance_statistics()", False)
    testArgs = _p.Param(
        "testArgs", "compat: extra VW CLI args applied at test/transform "
        "time in the reference; prediction here is a pure jit forward pass",
        "")

    def _extract(self, df: DataFrame) -> Tuple[SparseFeatures, np.ndarray,
                                               np.ndarray]:
        eff = self._effective_params()
        feats = _assemble_features(
            df, self.get("featuresCol"), self.get("additionalFeatures"),
            eff["interactions"], eff["ignore"], eff["numBits"])
        y = np.asarray(df[self.get("labelCol")], np.float32)
        wcol = self.get("weightCol")
        w = (np.asarray(df[wcol], np.float32) if wcol and wcol in df
             else np.ones(len(df), np.float32))
        return feats, y, w

    #: auto-shard row floor: below this the serial stream wins (sharding
    #: overhead + per-shard averaging noise buy nothing on small data)
    AUTO_SHARD_MIN_ROWS = 1 << 17

    def _resolve_num_tasks(self, n_rows: int) -> int:
        """numTasks=0 (the default) is auto: the mesh is the default data
        layout at scale — all local devices once the dataset can amortize
        sharding, one device below the floor. Explicit values are
        honored verbatim."""
        nt = self.get("numTasks")
        if nt:
            return int(nt)
        ndev = jax.local_device_count()
        return ndev if (ndev > 1 and n_rows >= self.AUTO_SHARD_MIN_ROWS) \
            else 1

    def _initial_state(self, nf: int) -> VWState:
        """Fresh table, or the initialModel warm start: weights/bias seed
        training while the adaptive accumulators restart (the reference
        reloads full VW state from model bytes — here the model's
        persisted surface is the weight table)."""
        init_m = self.get("initialModel")
        if init_m is None:
            return init_state(nf)
        if isinstance(init_m, VWState):
            prev_w = np.asarray(init_m.w)
            prev_b = float(init_m.bias)
        else:  # fitted VowpalWabbit model: weights + bias params
            prev_w = np.asarray(init_m.get("weights"))
            prev_b = float(init_m.get("biasValue"))
        if prev_w.shape[0] != nf:
            raise ValueError(
                f"initialModel was trained with a {prev_w.shape[0]}-slot "
                f"weight table but this estimator uses {nf} "
                f"(numBits mismatch)")
        return init_state(nf)._replace(
            w=jnp.asarray(prev_w, jnp.float32),
            bias=jnp.asarray(prev_b, jnp.float32))

    def _train_state(self, feats: SparseFeatures, y: np.ndarray,
                     w: np.ndarray) -> Tuple[VWState, np.ndarray, Dict]:
        eff = self._effective_params()
        nf = 1 << int(eff["numBits"])
        ntasks = self._resolve_num_tasks(len(y))
        mb = self.get("minibatchSize")
        # row-invariant index detection (dense feature columns and their
        # interactions hash to the same index vector on every row): checked
        # on the REAL rows, before padding — pad rows carry value 0 and are
        # inert on both scatter paths, so they cannot break the
        # equivalence (sgd.VWConfig.shared_indices)
        fi = feats.indices
        shared = bool(fi.size) and bool((fi == fi[:1]).all())
        cfg = VWConfig(
            num_features=nf, loss=eff["loss"] or self._loss,
            learning_rate=float(eff["learningRate"]),
            power_t=float(eff["powerT"]), initial_t=float(eff["initialT"]),
            l1=float(eff["l1"]), l2=float(eff["l2"]),
            adaptive=bool(eff["adaptive"]), normalized=bool(eff["normalized"]),
            invariant=bool(eff["invariant"]),
            num_passes=int(eff["numPasses"]), minibatch=mb,
            use_constant=bool(eff["useConstant"]),
            shared_indices=shared,
            axis_name=meshlib.DATA_AXIS if ntasks > 1 else None,
            fused=self._resolve_fused(bool(eff["adaptive"]),
                                      bool(eff["normalized"])))
        train = make_train_fn(cfg)
        t_ingest = time.perf_counter_ns()
        idx, val, yy, ww = pad_examples(
            feats.indices, feats.values, y, w, mb * max(ntasks, 1))
        state = self._initial_state(nf)
        t_learn0 = time.perf_counter_ns()
        if ntasks > 1:
            from jax.sharding import PartitionSpec as P
            mesh = meshlib.get_mesh(ntasks)
            ax = meshlib.DATA_AXIS
            sharded = meshlib.shard_map(
                train, mesh=mesh,
                in_specs=(P(ax), P(ax), P(ax), P(ax), P()),
                out_specs=(P(), P()), check_vma=False)
            # the canonical sharded data layout (shard_rows: row padding
            # to the axis extent + NamedSharding placement + caller
            # weights folded with the padding mask) — pad_examples
            # already rounded rows to mb*ntasks, so the mask is all-ones
            # and shard_rows adds no further padding; each device's
            # example shard rides its own host link
            idx_s, val_s, y_s, w_s, _mask = meshlib.shard_rows(
                mesh, idx, val, yy, weights=ww)
            # the VWState pytree stays uncommitted (init_state zeros /
            # warm-start asarray): jit replicates it per in_specs P()
            # the VW train step rides the shared compile cache: a resumed
            # or re-scheduled worker with the same VWConfig + mesh extent
            # reuses the executable instead of paying full JIT
            state, losses = compilecache.cached_jit(
                sharded, key=("vw_train_sharded", cfg, ntasks),
                name="vw_train_sharded")(idx_s, val_s, y_s, w_s, state)
        else:
            state, losses = compilecache.cached_jit(
                train, key=("vw_train", cfg),
                name="vw_train")(idx, val, yy, ww, state)
        jax.block_until_ready(state.w)
        t_end = time.perf_counter_ns()
        stats = {
            "partitionId": np.arange(max(ntasks, 1)),
            "ingestTimeNs": np.full(max(ntasks, 1),
                                    t_learn0 - t_ingest, np.int64),
            "learnTimeNs": np.full(max(ntasks, 1), t_end - t_learn0, np.int64),
            "totalTimeNs": np.full(max(ntasks, 1), t_end - t_ingest, np.int64),
            "rows": np.full(max(ntasks, 1), len(y) // max(ntasks, 1)),
            "passes": np.full(max(ntasks, 1), cfg.num_passes),
        }
        learn_s = max((t_end - t_learn0) * 1e-9, 1e-9)
        obsbridge.publish_vw_step_metrics(
            examples_per_s=len(y) * cfg.num_passes / learn_s)
        return state, np.asarray(losses), stats

    def _make_model(self, state: VWState, losses, stats) -> "VowpalWabbitBaseModel":
        raise NotImplementedError

    def _decorate_model(self, model: "VowpalWabbitBaseModel"
                        ) -> "VowpalWabbitBaseModel":
        """Copy the featurization surface onto the fitted model —
        transform must expand the same namespaces/interactions as fit.
        Shared by the offline _fit and finalize_online."""
        for p in ("featuresCol", "labelCol"):
            model.set(p, self.get(p))
        eff = self._effective_params()
        model.set("numBits", eff["numBits"])
        model.set("interactions", list(eff["interactions"]))
        model.set("additionalFeatures",
                  list(self.get("additionalFeatures") or []))
        model.set("ignoreNamespaces", "".join(eff["ignore"]))
        model.set("link", eff["link"] or "identity")
        return model

    def _fit(self, df: DataFrame) -> "VowpalWabbitBaseModel":
        feats, y, w = self._extract(df)
        state, losses, stats = self._train_state(feats, y, w)
        return self._decorate_model(self._make_model(state, losses, stats))

    # --------------------------------------------------------- online loop

    def _online_label_transform(self):
        """Label mapping the online ring applies at staging time (the
        classifier's 0/1 -> ±1 conversion); None = labels pass through."""
        return None

    def _online_config(self) -> VWConfig:
        """The streaming step's VWConfig: single pass, no sharding, no
        shared-index assumption (streamed rows are not known to be
        row-invariant up front)."""
        eff = self._effective_params()
        nf = 1 << int(eff["numBits"])
        return VWConfig(
            num_features=nf, loss=eff["loss"] or self._loss,
            learning_rate=float(eff["learningRate"]),
            power_t=float(eff["powerT"]), initial_t=float(eff["initialT"]),
            l1=float(eff["l1"]), l2=float(eff["l2"]),
            adaptive=bool(eff["adaptive"]), normalized=bool(eff["normalized"]),
            invariant=bool(eff["invariant"]),
            num_passes=1, minibatch=self.get("minibatchSize"),
            use_constant=bool(eff["useConstant"]),
            shared_indices=False, axis_name=None,
            fused=self._resolve_fused(bool(eff["adaptive"]),
                                      bool(eff["normalized"])))

    def online_learner(self, **ring_kw):
        """Build the ahead-dispatched online ring (models/vw/online.py)
        for this estimator's engine configuration: submit hashed
        (indices, values, labels[, weights]) rows as they arrive, then
        `finalize_online(ring)` for the fitted model. Ring knobs
        (depth, width, clock, registry, donate) pass through; the
        telemetry cadence defaults to this estimator's metricsEvery.
        Pass ``state=`` (a restored VWState) to resume a prior learner
        instead of starting fresh — the online loop's preempt-resume
        path (train/online_loop.py). Explicit ``is None`` check: VWState
        is a NamedTuple of arrays, so its truthiness is ambiguous."""
        from .online import VWOnlineRing
        cfg = self._online_config()
        state = ring_kw.pop("state", None)
        if state is None:
            state = self._initial_state(cfg.num_features)
        ring_kw.setdefault("metrics_every", int(self.get("metricsEvery")))
        return VWOnlineRing(cfg, state,
                            label_transform=self._online_label_transform(),
                            **ring_kw)

    def finalize_online(self, ring) -> "VowpalWabbitBaseModel":
        """Drain the ring and wrap its state as a fitted model (same
        decoration as the offline _fit). The model's pass_losses carry
        the ring's metricsEvery-sampled loss trajectory."""
        state, aux = ring.finalize()
        ns = int(aux["wall_s"] * 1e9)
        stats = {
            "partitionId": np.array([0]),
            "ingestTimeNs": np.array([0], np.int64),
            "learnTimeNs": np.array([ns], np.int64),
            "totalTimeNs": np.array([ns], np.int64),
            "rows": np.array([aux["examples"]]),
            "passes": np.array([1]),
        }
        return self._decorate_model(
            self._make_model(state, aux["losses"], stats))


class VowpalWabbitBaseModel(Model, _p.HasFeaturesCol, _p.HasLabelCol,
                            _p.HasRawPredictionCol, _p.HasPredictionCol):
    """Fitted linear model. Batched jit inference replaces the per-row JNI
    predict loop (vw/VowpalWabbitBaseModel.scala:23-112)."""

    numBits = _p.Param("numBits", "log2 weight-table size", 18, int)
    weights = _p.Param("weights", "weight table [2^numBits]", None, complex=True)
    biasValue = _p.Param("biasValue", "constant term", 0.0, float)
    interactions = _p.Param("interactions", "-q interaction specs used at "
                            "fit time (replayed at transform)", None)
    additionalFeatures = _p.Param("additionalFeatures",
                                  "extra namespace columns", None)
    ignoreNamespaces = _p.Param("ignoreNamespaces",
                                "dropped namespace letters", "")
    link = _p.Param("link", "output link function: identity | logistic "
                    "(--link)", "identity")

    def __init__(self, state: Optional[VWState] = None, losses=None,
                 stats=None, **kw):
        super().__init__(**kw)
        if state is not None:
            self._set(weights=np.asarray(state.w),
                      biasValue=float(state.bias))
        self._losses = np.asarray(losses) if losses is not None else None
        self._stats = stats

    # ---- diagnostics DataFrame (vw TrainingStats, VowpalWabbitBase.scala:268-303)
    def get_performance_statistics(self) -> DataFrame:
        if not self._stats:
            return DataFrame({"partitionId": np.array([0])})
        return DataFrame(self._stats)

    getPerformanceStatistics = get_performance_statistics

    @property
    def pass_losses(self) -> Optional[np.ndarray]:
        return self._losses

    def _margin(self, df: DataFrame) -> np.ndarray:
        feats = _assemble_features(
            df, self.get("featuresCol"), self.get("additionalFeatures"),
            self.get("interactions"), list(self.get("ignoreNamespaces") or ""),
            self.get("numBits"))
        return np.asarray(_score_batch(
            jnp.asarray(self.get("weights")),
            jnp.float32(self.get("biasValue")),
            jnp.asarray(feats.indices), jnp.asarray(feats.values)))

    def _save_extra(self, path: str):
        import os
        if self._losses is not None:
            np.save(os.path.join(path, "pass_losses.npy"), self._losses)
        return {"has_losses": self._losses is not None}

    def _load_extra(self, path: str, extra) -> None:
        import os
        self._losses = None
        self._stats = None
        f = os.path.join(path, "pass_losses.npy")
        if extra.get("has_losses") and os.path.exists(f):
            self._losses = np.load(f)
