"""VowpalWabbitContextualBandit — ADF contextual bandit (``--cb_explore_adf``).

Reference: vw/VowpalWabbitContextualBandit.scala:30-359 — shared + per-action
namespaces, chosen action (1-based), logged probability, cost label;
`ContextualBanditMetrics` (:55-85) tracks the ips/snips policy-value estimators.

TPU design: the cost regressor for the chosen (shared ⊕ action) features is the
same jitted SGD engine, with importance weight 1/p — an IPS-weighted cost model.
Per-action scoring at transform time is one batched gather-dot over all actions.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame
from .base import VowpalWabbitBase, VowpalWabbitBaseModel
from .sparse import SparseFeatures


class ContextualBanditMetrics:
    """ips / snips estimators of policy value (reference :55-85)."""

    def __init__(self):
        self.total_events = 0
        self.total_ips_numerator = 0.0
        self.total_snips_denominator = 0.0

    def add(self, probability_logged: float, cost: float,
            probability_predicted: float = 1.0) -> None:
        w = probability_predicted / max(probability_logged, 1e-9)
        self.total_events += 1
        self.total_ips_numerator += cost * w
        self.total_snips_denominator += w

    @property
    def ips_estimate(self) -> float:
        return (self.total_ips_numerator / self.total_events
                if self.total_events else 0.0)

    @property
    def snips_estimate(self) -> float:
        return (self.total_ips_numerator / self.total_snips_denominator
                if self.total_snips_denominator else 0.0)


def _row_features(item) -> Tuple[np.ndarray, np.ndarray]:
    if item is None:
        return np.zeros(0, np.int64), np.zeros(0, np.float32)
    if isinstance(item, tuple):
        return (np.asarray(item[0], np.int64), np.asarray(item[1], np.float32))
    arr = np.asarray(item, np.float32).ravel()
    return np.nonzero(arr)[0].astype(np.int64), arr[arr != 0.0]


class VowpalWabbitContextualBandit(VowpalWabbitBase, _p.HasPredictionCol):
    _loss = "squared"

    sharedCol = _p.Param("sharedCol", "shared (context) features column",
                         "shared")
    additionalSharedFeatures = _p.Param(
        "additionalSharedFeatures",
        "extra shared-feature columns concatenated with sharedCol "
        "(VowpalWabbitContextualBandit additionalSharedFeatures)", None)
    chosenActionCol = _p.Param("chosenActionCol",
                               "1-based chosen action index", "chosenAction")
    probabilityCol = _p.Param("probabilityCol",
                              "logged probability of the chosen action",
                              "probability")
    epsilon = _p.Param("epsilon", "epsilon-greedy exploration rate for the "
                       "returned action distribution", 0.05, float)

    def __init__(self, **kw):
        kw.setdefault("labelCol", "cost")
        super().__init__(**kw)

    def _build_event_rows(self, df: DataFrame,
                          metrics: ContextualBanditMetrics = None
                          ) -> Tuple[SparseFeatures, np.ndarray, np.ndarray]:
        """Assemble the (shared ⊕ chosen-action) training rows, IPS
        weights, and policy-value metrics from a logged-events frame —
        shared by the offline _fit and the online submit_events path."""
        actions_col = df[self.get("featuresCol")]
        shared_col = (df[self.get("sharedCol")]
                      if self.get("sharedCol") in df else None)
        extra_shared = [df[c] for c in
                        (self.get("additionalSharedFeatures") or [])
                        if c in df]
        chosen = np.asarray(df[self.get("chosenActionCol")], np.int64)
        prob = np.asarray(df[self.get("probabilityCol")], np.float64)
        cost = np.asarray(df[self.get("labelCol")], np.float32)

        nf = 1 << self.get("numBits")
        rows: List[Tuple[np.ndarray, np.ndarray]] = []
        for i in range(len(df)):
            if not 1 <= chosen[i] <= len(actions_col[i]):
                raise ValueError(
                    f"chosenAction is 1-based (reference CB ADF convention); "
                    f"row {i} has {chosen[i]} with {len(actions_col[i])} actions")
            a_idx, a_val = _row_features(actions_col[i][chosen[i] - 1])
            if shared_col is not None:
                s_idx, s_val = _row_features(shared_col[i])
                a_idx = np.concatenate([s_idx, a_idx])
                a_val = np.concatenate([s_val, a_val])
            for ecol in extra_shared:
                e_idx, e_val = _row_features(ecol[i])
                a_idx = np.concatenate([e_idx, a_idx])
                a_val = np.concatenate([e_val, a_val])
            rows.append((a_idx % nf, a_val))
            if metrics is not None:
                metrics.add(float(prob[i]), float(cost[i]))
        feats = SparseFeatures.from_rows(rows, nf)
        # IPS: cost regression importance-weighted by 1/p (capped for stability)
        w = np.minimum(1.0 / np.maximum(prob, 1e-6), 1e3).astype(np.float32)
        return feats, cost, w

    def _fit(self, df: DataFrame) -> "VowpalWabbitContextualBanditModel":
        metrics = ContextualBanditMetrics()
        feats, cost, w = self._build_event_rows(df, metrics)
        state, losses, stats = self._train_state(feats, cost, w)
        model = self._make_model(state, losses, stats)
        model._metrics = metrics
        return model

    def _make_model(self, state, losses, stats):
        model = VowpalWabbitContextualBanditModel(state=state, losses=losses,
                                                  stats=stats)
        for p in ("featuresCol", "sharedCol", "predictionCol"):
            model.set(p, self.get(p))
        model.set("numBits", self._effective_params()["numBits"])
        model.set("epsilon", self.get("epsilon"))
        model.set("additionalSharedFeatures",
                  list(self.get("additionalSharedFeatures") or []))
        return model

    def _decorate_model(self, model):
        # finalize_online routes through here; _make_model already carried
        # the bandit surface and the base decoration's namespace replay
        # does not apply to ADF event rows
        return model

    def submit_events(self, ring, df: DataFrame,
                      metrics: ContextualBanditMetrics = None) -> int:
        """Feed one logged-events frame through the online ring: the same
        (shared ⊕ chosen-action) rows and capped-IPS weights as _fit,
        staged/ahead-dispatched by the ring. Accumulates policy-value
        estimators into `metrics` when given; returns the number of
        device steps dispatched."""
        feats, cost, w = self._build_event_rows(df, metrics)
        return ring.submit(feats.indices, feats.values, cost, w)

    def finalize_online(self, ring,
                        metrics: ContextualBanditMetrics = None
                        ) -> "VowpalWabbitContextualBanditModel":
        model = super().finalize_online(ring)
        model._metrics = metrics or ContextualBanditMetrics()
        return model

    def parallel_fit(self, df: DataFrame, param_maps) -> list:
        """Fit one model per param map concurrently — the reference's custom
        `fit(df, paramMaps)` thread-parallel search
        (VowpalWabbitContextualBandit.scala:300-359). Each map is a
        {paramName: value} dict applied over this estimator's settings."""
        from concurrent.futures import ThreadPoolExecutor

        pms = list(param_maps)
        if not pms:
            return []

        def one(pm):
            est = self.copy(dict(pm))
            return est.fit(df)

        with ThreadPoolExecutor(max_workers=min(len(pms), 8)) as ex:
            return list(ex.map(one, pms))

    parallelFit = parallel_fit


class VowpalWabbitContextualBanditModel(VowpalWabbitBaseModel):
    sharedCol = _p.Param("sharedCol", "shared (context) features column",
                         "shared")
    additionalSharedFeatures = _p.Param(
        "additionalSharedFeatures",
        "extra shared-feature columns concatenated with sharedCol", None)
    epsilon = _p.Param("epsilon", "epsilon-greedy exploration rate", 0.05,
                       float)

    def __init__(self, **kw):
        super().__init__(**kw)
        self._metrics = None

    def get_contextual_bandit_metrics(self) -> ContextualBanditMetrics:
        return self._metrics or ContextualBanditMetrics()

    getContextualBanditMetrics = get_contextual_bandit_metrics

    def transform(self, df: DataFrame) -> DataFrame:
        """Emit per-action predicted costs and an epsilon-greedy action
        distribution (cb_explore_adf output shape).

        Scoring is ONE batched cached_jit call over every (row, action)
        pair — the per-row-per-action numpy dot loop this replaces paid
        python overhead per action AND dodged the compile cache the rest
        of the serving surface rides (ISSUE 16 satellite: the bandit
        scoring path routes through vw_score like _margin does)."""
        import jax.numpy as jnp

        from .base import _score_batch

        actions_col = df[self.get("featuresCol")]
        shared_col = (df[self.get("sharedCol")]
                      if self.get("sharedCol") in df else None)
        extra_shared = [df[c] for c in
                        (self.get("additionalSharedFeatures") or [])
                        if c in df]
        w = np.asarray(self.get("weights"), np.float32)
        b = self.get("biasValue")
        eps = self.get("epsilon")
        nf = len(w)
        # host-side assembly: one (shared ⊕ action) sparse row per
        # (row, action) pair; the padded batch scores in a single call
        rows: List[Tuple[np.ndarray, np.ndarray]] = []
        counts = np.empty(len(df), np.int64)
        for i in range(len(df)):
            s_idx, s_val = (_row_features(shared_col[i])
                            if shared_col is not None
                            else (np.zeros(0, np.int64),
                                  np.zeros(0, np.float32)))
            for ecol in extra_shared:
                e_idx, e_val = _row_features(ecol[i])
                s_idx = np.concatenate([e_idx, s_idx])
                s_val = np.concatenate([e_val, s_val])
            counts[i] = len(actions_col[i])
            for action in actions_col[i]:
                a_idx, a_val = _row_features(action)
                rows.append((np.concatenate([s_idx, a_idx]) % nf,
                             np.concatenate([s_val, a_val])))
        feats = SparseFeatures.from_rows(rows, nf)
        margins = np.asarray(_score_batch(
            jnp.asarray(w), jnp.float32(b),
            jnp.asarray(feats.indices), jnp.asarray(feats.values)),
            np.float64)
        preds = np.empty(len(df), dtype=object)
        dists = np.empty(len(df), dtype=object)
        offsets = np.concatenate([[0], np.cumsum(counts)])
        for i in range(len(df)):
            scores = margins[offsets[i]:offsets[i + 1]]
            k = len(scores)
            dist = np.full(k, eps / k)
            dist[int(scores.argmin())] += 1.0 - eps  # min predicted cost
            preds[i] = scores
            dists[i] = dist
        return (df.with_column(self.get("predictionCol"), preds)
                  .with_column("probabilities", dists))
