"""VowpalWabbitFeaturizer + VowpalWabbitInteractions — hashed sparse features.

Reference: vw/VowpalWabbitFeaturizer.scala:22-226 (columns -> hashed SparseVector
with per-type featurizer dispatch, JVM murmur — no JNI) and the per-type impls in
vw/featurizer/*.scala (Numeric/String/Boolean/Map/Seq/Vector/StringSplit).
Namespace-prefix hashing mirrors vw/VowpalWabbitMurmurWithPrefix.scala:77.
VowpalWabbitInteractions (vw/VowpalWabbitInteractions.scala:89) is the JVM-side
`-q` quadratic-interaction transformer.

String and split-token columns hash through the batched host path
(utils/hashing.hash_strings — C++ kernel when available); other object cells
fall back to per-value python hashing. The resulting fixed-width sparse batch
feeds the jit SGD engine directly.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame
from ...core.pipeline import Transformer
from ...utils.hashing import MurmurWithPrefix, hash_strings, murmur3_32
from .sparse import SparseFeatures


class HasNumBits(_p.Params):
    numBits = _p.Param(
        "numBits", "log2 of the feature-table size (VW -b); weights table is "
        "dense in HBM so the practical ceiling is ~24", 18, int)


class HasSumCollisions(_p.Params):
    sumCollisions = _p.Param(
        "sumCollisions", "sum values of colliding hashes (else last wins)",
        True, bool)


class VowpalWabbitFeaturizer(Transformer, _p.HasInputCols, _p.HasOutputCol,
                             HasNumBits, HasSumCollisions):
    seed = _p.Param("seed", "murmur hash seed", 0, int)
    stringSplitInputCols = _p.Param(
        "stringSplitInputCols",
        "string columns split on whitespace into multiple hashed tokens", None)
    prefixStringsWithColumnName = _p.Param(
        "prefixStringsWithColumnName",
        "prefix string values with their column name before hashing "
        "(VowpalWabbitFeaturizer.scala default)", True)
    preserveOrderNumBits = _p.Param(
        "preserveOrderNumBits",
        "reserve this many high hash bits for the column index, so features "
        "from different columns never collide (0 = off)", 0, int)

    def __init__(self, **kw):
        kw.setdefault("outputCol", "features")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        cols = list(self.get("inputCols") or [])
        split_cols = list(self.get("stringSplitInputCols") or [])
        num_bits = self.get("numBits")
        mask = (1 << num_bits) - 1
        seed = self.get("seed")
        prefix = self.get("prefixStringsWithColumnName")
        p_bits = self.get("preserveOrderNumBits")
        if p_bits < 0 or p_bits >= num_bits:
            raise ValueError("preserveOrderNumBits must be in [0, numBits)")
        if p_bits and len(cols) + len(split_cols) > (1 << p_bits):
            # reference throws rather than silently aliasing columns
            # (VowpalWabbitFeaturizer.scala:187-190)
            raise ValueError(
                f"too many input columns ({len(cols) + len(split_cols)}) for "
                f"preserveOrderNumBits={p_bits} (capacity {1 << p_bits})")
        low_bits = num_bits - p_bits
        low_mask = (1 << low_bits) - 1
        n = len(df)
        rows: List[Tuple[List[int], List[float]]] = [([], []) for _ in range(n)]

        for ci, name in enumerate(cols + split_cols):
            if p_bits:
                hi = ci << low_bits

                def place(b, _hi=hi):
                    return _hi | (int(b) & low_mask)
            else:
                def place(b):
                    return int(b)
            col = df[name]
            hasher = MurmurWithPrefix(name if prefix else "", seed)
            if name in split_cols:
                # batch path: one native hash_strings call for all tokens
                toks, owners = [], []
                for i in range(n):
                    v = col[i]
                    if v is None:
                        continue
                    for tok in str(v).split():
                        toks.append((name + tok) if prefix else tok)
                        owners.append(i)
                if toks:
                    buckets = hash_strings(toks, num_bits, seed)
                    for i, b in zip(owners, buckets):
                        rows[i][0].append(place(b))
                        rows[i][1].append(1.0)
            elif col.dtype == object and len(col) and isinstance(
                    next((v for v in col if v is not None), None), str):
                # plain string column: batch-hash name+value
                live = [i for i in range(n) if col[i] is not None]
                buckets = hash_strings(
                    [(name + col[i]) if prefix else col[i] for i in live],
                    num_bits, seed)
                for i, b in zip(live, buckets):
                    rows[i][0].append(place(b))
                    rows[i][1].append(1.0)
            elif col.dtype == object:
                for i in range(n):
                    self._featurize_obj(rows[i], col[i], name, hasher, mask,
                                        seed, place)
            elif col.dtype.kind in "fiu":
                if col.ndim == 2:  # dense vector column: index by position
                    base = [place(murmur3_32(f"{name}_{j}".encode(), seed)
                                  & mask)
                            for j in range(col.shape[1])]
                    for i in range(n):
                        for j, v in enumerate(col[i]):
                            if v != 0.0:
                                rows[i][0].append(base[j])
                                rows[i][1].append(float(v))
                else:  # numeric scalar: one slot per column, value = number
                    h = place(murmur3_32(name.encode(), seed) & mask)
                    for i in range(n):
                        v = float(col[i])
                        if v != 0.0:
                            rows[i][0].append(h)
                            rows[i][1].append(v)
            elif col.dtype.kind == "b":
                h = place(murmur3_32(name.encode(), seed) & mask)
                for i in range(n):
                    if col[i]:
                        rows[i][0].append(h)
                        rows[i][1].append(1.0)
            else:
                raise TypeError(f"unsupported column dtype {col.dtype} "
                                f"for {name!r}")

        packed = self._pack(rows, mask + 1)
        return df.with_column(self.get("outputCol"), packed.to_object_column(),
                              metadata={"numFeatures": mask + 1,
                                        "sparse": True})

    @staticmethod
    def _featurize_obj(row, value, name, hasher: MurmurWithPrefix, mask: int,
                       seed: int, place=int) -> None:
        """Per-type dispatch for object cells (vw/featurizer/*.scala)."""
        if value is None:
            return
        if isinstance(value, str):
            row[0].append(place(hasher.hash(value) & mask))
            row[1].append(1.0)
        elif isinstance(value, dict):
            for k, v in value.items():
                if isinstance(v, str):
                    row[0].append(place(hasher.hash(f"{k}{v}") & mask))
                    row[1].append(1.0)
                else:
                    row[0].append(place(hasher.hash(str(k)) & mask))
                    row[1].append(float(v))
        elif isinstance(value, (list, tuple, np.ndarray)):
            for pos, item in enumerate(value):
                if isinstance(item, str):
                    row[0].append(place(hasher.hash(item) & mask))
                    row[1].append(1.0)
                else:  # numeric sequence: slot keyed by position in the seq
                    row[0].append(place(hasher.hash(str(pos)) & mask))
                    row[1].append(float(item))
        elif isinstance(value, (bool, np.bool_)):
            if value:
                row[0].append(place(hasher.hash("") & mask))
                row[1].append(1.0)
        else:
            row[0].append(place(hasher.hash("") & mask))
            row[1].append(float(value))

    def _pack(self, rows, num_features: int) -> SparseFeatures:
        sum_collisions = self.get("sumCollisions")
        out = []
        for idx, val in rows:
            idx_a = np.asarray(idx, np.int64)
            val_a = np.asarray(val, np.float32)
            if len(idx_a) > 1:
                uniq, inv = np.unique(idx_a, return_inverse=True)
                if len(uniq) < len(idx_a):
                    merged = np.zeros(len(uniq), np.float32)
                    if sum_collisions:
                        np.add.at(merged, inv, val_a)
                    else:
                        merged[inv] = val_a
                    idx_a, val_a = uniq, merged
            out.append((idx_a, val_a))
        return SparseFeatures.from_rows(out, num_features)


class VowpalWabbitInteractions(Transformer, _p.HasInputCols, _p.HasOutputCol,
                               HasNumBits, HasSumCollisions):
    """Quadratic (and higher) feature interactions — VW `-q` done host-side.

    Reference: vw/VowpalWabbitInteractions.scala:89 — for N input (hashed sparse)
    columns, emit the outer product of their features: combined hash, multiplied
    values. Input columns must be VowpalWabbitFeaturizer outputs (or dense)."""

    def __init__(self, **kw):
        kw.setdefault("outputCol", "interactions")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        cols = list(self.get("inputCols") or [])
        if len(cols) < 2:
            raise ValueError("interactions need >= 2 input columns")
        num_bits = self.get("numBits")
        mask = (1 << num_bits) - 1
        feats = [SparseFeatures.from_column(df[c]) for c in cols]
        n = len(df)
        rows = []
        for i in range(n):
            idx = feats[0].indices[i].astype(np.int64)
            val = feats[0].values[i].astype(np.float64)
            live = val != 0.0
            idx, val = idx[live], val[live]
            for f in feats[1:]:
                j_idx = f.indices[i].astype(np.int64)
                j_val = f.values[i].astype(np.float64)
                jl = j_val != 0.0
                j_idx, j_val = j_idx[jl], j_val[jl]
                # FNV-1a-style combine of the two hashed indices (VW interact())
                idx = ((idx[:, None] * 0x01000193 ^ j_idx[None, :]) & mask
                       ).reshape(-1)
                val = (val[:, None] * j_val[None, :]).reshape(-1)
            rows.append((idx, val.astype(np.float32)))
        packed = SparseFeatures.from_rows(rows, mask + 1)
        return df.with_column(self.get("outputCol"), packed.to_object_column(),
                              metadata={"numFeatures": mask + 1,
                                        "sparse": True})


class VectorZipper(Transformer, _p.HasInputCols, _p.HasOutputCol):
    """Zip several columns row-wise into one array column
    (vw/VectorZipper.scala:37 — the namespace-assembly helper that feeds
    multi-namespace VW examples; generic enough for any consumer that
    wants a per-row sequence of column values)."""

    def __init__(self, **kw):
        kw.setdefault("outputCol", "zipped")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("inputCols")
        if not cols:
            raise ValueError("VectorZipper needs inputCols")
        missing = [c for c in cols if c not in df]
        if missing:
            raise KeyError(f"VectorZipper: missing columns {missing}")
        series = [df[c] for c in cols]
        out = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            out[i] = [s[i] for s in series]
        return df.with_column(self.get("outputCol"), out)
