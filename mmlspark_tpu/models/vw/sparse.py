"""Fixed-width sparse feature batches for the VW-equivalent learners.

The reference marshals each row into a native ``VowpalWabbitExample`` (sparse
index/value pairs per namespace — vw/VowpalWabbitBase.scala:235-266,
vw/VectorUtils.scala). A TPU kernel wants static shapes, so the batch layout here
is a padded COO pair ``(indices[n,k], values[n,k])`` with k = max nnz per row.
Padding slots carry ``(index=0, value=0.0)``: a zero value contributes nothing to
either the dot product or the gradient scatter, so no mask is needed in the kernel
(the masking discipline of SURVEY.md §7 "empty/skewed shards").
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


class SparseFeatures:
    """A batch of hashed sparse feature rows with a fixed per-row width."""

    __slots__ = ("indices", "values", "num_features")

    def __init__(self, indices: np.ndarray, values: np.ndarray,
                 num_features: int):
        assert indices.shape == values.shape and indices.ndim == 2
        self.indices = np.ascontiguousarray(indices, np.int32)
        self.values = np.ascontiguousarray(values, np.float32)
        self.num_features = int(num_features)

    def __len__(self) -> int:
        return self.indices.shape[0]

    @property
    def width(self) -> int:
        return self.indices.shape[1]

    def take(self, idx: np.ndarray) -> "SparseFeatures":
        return SparseFeatures(self.indices[idx], self.values[idx],
                              self.num_features)

    def to_dense(self) -> np.ndarray:
        out = np.zeros((len(self), self.num_features), np.float32)
        rows = np.repeat(np.arange(len(self)), self.width)
        np.add.at(out, (rows, self.indices.ravel()), self.values.ravel())
        return out

    @staticmethod
    def from_rows(rows: Sequence[Tuple[np.ndarray, np.ndarray]],
                  num_features: int, min_width: int = 1) -> "SparseFeatures":
        """Pack per-row (indices, values) pairs into a padded batch.

        Duplicate indices within one row are kept as-is — the dot product and
        the scatter-add both sum duplicates, matching hash-collision-by-sum
        semantics (vw featurizer sums colliding features when sumCollisions)."""
        k = max(min_width, max((len(i) for i, _ in rows), default=1))
        n = len(rows)
        indices = np.zeros((n, k), np.int32)
        values = np.zeros((n, k), np.float32)
        for r, (idx, val) in enumerate(rows):
            m = len(idx)
            indices[r, :m] = idx
            values[r, :m] = val
        return SparseFeatures(indices, values, num_features)

    @staticmethod
    def from_dense(x: np.ndarray, num_features: int = 0) -> "SparseFeatures":
        """Dense matrix -> trivially sparse batch (indices = column ids)."""
        x = np.asarray(x, np.float32)
        n, f = x.shape
        indices = np.broadcast_to(np.arange(f, dtype=np.int32), (n, f))
        return SparseFeatures(indices.copy(), x, max(num_features, f))

    def to_object_column(self) -> np.ndarray:
        """Store in a DataFrame as an object column of (indices, values) pairs."""
        out = np.empty(len(self), dtype=object)
        for i in range(len(self)):
            out[i] = (self.indices[i], self.values[i])
        return out

    @staticmethod
    def from_column(col: np.ndarray, num_features: int = 0) -> "SparseFeatures":
        """Accept either a dense 2-D float column or an object column of
        (indices, values) pairs (as produced by VowpalWabbitFeaturizer)."""
        if col.dtype != object:
            arr = np.asarray(col, np.float32)
            if arr.ndim != 2:
                arr = arr.reshape(len(arr), -1)
            return SparseFeatures.from_dense(arr, num_features)
        rows: List[Tuple[np.ndarray, np.ndarray]] = []
        nf = num_features
        for item in col:
            idx, val = item
            idx = np.asarray(idx, np.int64)
            rows.append((idx, np.asarray(val, np.float32)))
            if idx.size:
                nf = max(nf, int(idx.max()) + 1)
        return SparseFeatures.from_rows(rows, nf)
