"""VowpalWabbitClassifier / VowpalWabbitRegressor.

Reference: vw/VowpalWabbitClassifier.scala:23-105 (logistic link, raw/probability
columns, labels mapped to VW's {-1,+1}) and vw/VowpalWabbitRegressor.scala:1-55.
"""

from __future__ import annotations

import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame
from .base import VowpalWabbitBase, VowpalWabbitBaseModel


class VowpalWabbitClassifier(VowpalWabbitBase, _p.HasProbabilityCol,
                             _p.HasRawPredictionCol, _p.HasPredictionCol):
    _loss = "logistic"

    labelConversion = _p.Param(
        "labelConversion",
        "convert 0/1 Spark-style labels to -1/+1 VW style "
        "(VowpalWabbitClassifier.scala:31-35); turn off when labels already "
        "carry the VW convention", True)

    def _extract(self, df: DataFrame):
        feats, y, w = super()._extract(df)
        if self.get("labelConversion"):
            # 0/1 labels -> VW logistic convention {-1,+1}
            y = np.where(y > 0.5, 1.0, -1.0).astype(np.float32)
        else:
            bad = ~np.isin(y, (-1.0, 1.0))
            if bad.any():
                raise ValueError(
                    "labelConversion=False requires labels in {-1, +1}; "
                    f"found {np.unique(y[bad])[:5]}")
        return feats, y, w

    def _online_label_transform(self):
        """Same labelConversion contract as _extract, applied per staged
        chunk by the online ring."""
        if not self.get("labelConversion"):
            def _check(y):
                bad = ~np.isin(y, (-1.0, 1.0))
                if bad.any():
                    raise ValueError(
                        "labelConversion=False requires labels in {-1, +1}; "
                        f"found {np.unique(y[bad])[:5]}")
                return y
            return _check
        return lambda y: np.where(y > 0.5, 1.0, -1.0).astype(np.float32)

    def _make_model(self, state, losses, stats):
        model = VowpalWabbitClassificationModel(state=state, losses=losses,
                                                stats=stats)
        for p in ("probabilityCol", "rawPredictionCol", "predictionCol"):
            model.set(p, self.get(p))
        return model


class VowpalWabbitClassificationModel(VowpalWabbitBaseModel,
                                      _p.HasProbabilityCol):
    def transform(self, df: DataFrame) -> DataFrame:
        margin = self._margin(df)
        prob1 = 1.0 / (1.0 + np.exp(-margin))
        probs = np.stack([1.0 - prob1, prob1], axis=1)
        raws = np.stack([-margin, margin], axis=1)
        pred = (margin > 0).astype(np.float64)
        return (df.with_column(self.get("rawPredictionCol"), raws)
                  .with_column(self.get("probabilityCol"), probs)
                  .with_column(self.get("predictionCol"), pred))


class VowpalWabbitRegressor(VowpalWabbitBase, _p.HasPredictionCol):
    _loss = "squared"

    def _make_model(self, state, losses, stats):
        model = VowpalWabbitRegressionModel(state=state, losses=losses,
                                            stats=stats)
        model.set("predictionCol", self.get("predictionCol"))
        return model


class VowpalWabbitRegressionModel(VowpalWabbitBaseModel):
    def transform(self, df: DataFrame) -> DataFrame:
        margin = self._margin(df)
        if self.get("link") == "logistic":
            # VW --link logistic: sigmoid applied to the output
            margin = 1.0 / (1.0 + np.exp(-margin))
        return df.with_column(self.get("predictionCol"),
                              margin.astype(np.float64))
