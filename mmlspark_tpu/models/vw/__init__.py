"""VowpalWabbit-equivalent online linear learners (reference: vw/, 24 files).

The C++ `vw-jni` engine + spanning-tree allreduce are replaced by a jitted
minibatch SGD program sharded over the device mesh (models/vw/sgd.py)."""

from .base import VowpalWabbitBase, VowpalWabbitBaseModel
from .classifier import (VowpalWabbitClassifier,
                         VowpalWabbitClassificationModel,
                         VowpalWabbitRegressor, VowpalWabbitRegressionModel)
from .contextual_bandit import (ContextualBanditMetrics,
                                VowpalWabbitContextualBandit,
                                VowpalWabbitContextualBanditModel)
from .featurizer import (VectorZipper, VowpalWabbitFeaturizer,
                         VowpalWabbitInteractions)
from .online import VWOnlineRing
from .sparse import SparseFeatures

__all__ = [
    "VowpalWabbitBase", "VowpalWabbitBaseModel",
    "VowpalWabbitClassifier", "VowpalWabbitClassificationModel",
    "VowpalWabbitRegressor", "VowpalWabbitRegressionModel",
    "VowpalWabbitContextualBandit", "VowpalWabbitContextualBanditModel",
    "ContextualBanditMetrics", "VWOnlineRing",
    "VowpalWabbitFeaturizer", "VowpalWabbitInteractions", "VectorZipper",
    "SparseFeatures",
]
