"""VW online-learning ring — ahead-dispatched minibatch steps (ISSUE 16).

The offline fit amortizes dispatch over a whole `lax.scan`; the online
loop cannot (examples arrive incrementally), and a naive implementation
syncs host<->device once per step — the per-example-overhead trap of
arxiv 1612.01437 applied to a step loop. This module applies the PR 6
ahead-dispatch discipline to the online path instead:

- `submit()` stages incoming rows in a host-side tail buffer and
  dispatches one device step per full minibatch WITHOUT waiting for the
  previous step: JAX dispatch is async, so batch i+1's staging (numpy
  slicing, label transform, width pinning) runs on the host while step
  i executes on the device.
- A bounded ring (`depth`) of in-flight steps provides backpressure:
  when full, the dispatcher blocks ONLY in `_retire_oldest`, the
  designated sync point under the AST sync-point lint
  (tests/test_fit_pipeline.py::TestSyncPointLint) — `submit`/`_dispatch`
  themselves must stay free of host fetches.
- Telemetry never forces a per-step sync: the loss scalar is fetched to
  host every `metrics_every` retired steps (the step is already retired
  — blocked — when fetched, so the fetch itself is free), publishing
  `vw_examples_per_s` / `vw_step_seconds` via observability/bridge.py.
- The device step is `make_step_fn(cfg)` routed through `cached_jit`
  (key `("vw_online_step", cfg, donate)`), with the carry donated on
  real accelerators so the packed table updates in place.

The carry is the fused packed table when cfg.fused (ONE gather + ONE
scatter per step — see sgd._fused_minibatch_step) and a plain VWState
otherwise. With donation active the ring owns the initial state's
buffers; callers must not reuse a donated VWState after the first step.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...compile import cache as compilecache
from ...observability import bridge as obsbridge
from .sgd import (VWConfig, VWState, init_state, make_step_fn, pack_state,
                  pad_examples, unpack_state)

__all__ = ["VWOnlineRing"]


def _coerce_rows(indices, values, labels, weights):
    """Host-side staging coercion (called from the hot path but pure
    numpy-on-host: the inputs are caller rows, never device arrays, so
    nothing here can become an implicit device fetch)."""
    idx = np.asarray(indices, np.int32)
    val = np.asarray(values, np.float32)
    y = np.asarray(labels, np.float32)
    if idx.ndim != 2 or val.shape != idx.shape:
        raise ValueError(
            f"expected row-major [n, k] indices/values, got {idx.shape} / "
            f"{val.shape}")
    if y.shape != (idx.shape[0],):
        raise ValueError(
            f"labels must be [n]={idx.shape[0]}, got {y.shape}")
    w = (np.ones(len(y), np.float32) if weights is None
         else np.asarray(weights, np.float32))
    if w.shape != y.shape:
        raise ValueError(f"weights must be [n]={y.shape}, got {w.shape}")
    return idx, val, y, w


def _repin_width(idx, val, k_pinned: int):
    """Pad a narrower chunk up to the ring's pinned row width (index 0 /
    value 0 slots are inert). A WIDER chunk would change the jitted step's
    shape and retrace mid-stream — stalling the very overlap the ring
    exists to create — so it is rejected loudly instead."""
    k = idx.shape[1]
    if k > k_pinned:
        raise ValueError(
            f"row width {k} exceeds the ring's pinned width {k_pinned}; "
            f"a wider batch would retrace the jitted step mid-stream. "
            f"Create the ring with width={k} (or submit the widest batch "
            f"first)")
    if k == k_pinned:
        return idx, val
    pad = ((0, 0), (0, k_pinned - k))
    return (np.pad(idx, pad), np.pad(val, pad))


class VWOnlineRing:
    """Bounded ahead-dispatch ring over the VW minibatch step.

    Usage::

        ring = estimator.online_learner()
        for chunk in stream:
            ring.submit(chunk.indices, chunk.values, chunk.labels)
        model = estimator.finalize_online(ring)

    Rows below one minibatch accumulate in the tail buffer until enough
    arrive; `flush()` pads the tail with zero-weight rows (inert through
    the step) and drains every in-flight step.
    """

    def __init__(self, cfg: VWConfig, state: Optional[VWState] = None, *,
                 depth: int = 2, metrics_every: int = 10,
                 label_transform: Optional[Callable] = None,
                 width: Optional[int] = None,
                 registry=None, clock: Callable[[], float] = time.perf_counter,
                 donate: Optional[bool] = None):
        if depth < 1:
            raise ValueError(f"ring depth must be >= 1, got {depth}")
        if metrics_every < 1:
            raise ValueError(
                f"metricsEvery must be >= 1, got {metrics_every}")
        self.cfg = cfg
        self._template = (state if state is not None
                          else init_state(cfg.num_features))
        self._carry = (pack_state(cfg, self._template) if cfg.fused
                       else self._template)
        if donate is None:
            # donation is a no-op warning on CPU; only arm it on real chips
            donate = jax.default_backend() != "cpu"
        dn = (0,) if donate else ()
        self._step = compilecache.cached_jit(
            make_step_fn(cfg), key=("vw_online_step", cfg, dn),
            name="vw_online_step", donate_argnums=dn)
        self._depth = depth
        self._metrics_every = metrics_every
        self._label_transform = label_transform
        self._registry = registry
        self._clock = clock
        self._k: Optional[int] = width
        self._tail: Optional[List[np.ndarray]] = None
        self._inflight: deque = deque()  # (loss_dev, n_examples, t_dispatch)
        self._loss_history: List[Tuple[int, float]] = []
        self._steps = 0
        self._retired = 0
        self._examples = 0
        self._examples_retired = 0
        self._t_start: Optional[float] = None
        self._last_loss: Optional[float] = None

    # ------------------------------------------------------------ hot path

    def submit(self, indices, values, labels, weights=None) -> int:
        """Stage rows and ahead-dispatch every full minibatch. Returns the
        number of device steps dispatched. HOT PATH: no host fetch happens
        here — backpressure blocking lives in _retire_oldest (the
        designated sync point)."""
        idx, val, y, w = _coerce_rows(indices, values, labels, weights)
        if self._label_transform is not None:
            y = self._label_transform(y)
        if self._k is None:
            self._k = idx.shape[1]
        elif idx.shape[1] != self._k:
            idx, val = _repin_width(idx, val, self._k)
        if self._tail is None:
            self._tail = [idx, val, y, w]
        else:
            self._tail = [np.concatenate([a, b]) for a, b in
                          zip(self._tail, (idx, val, y, w))]
        ti, tv, ty, tw = self._tail
        b = self.cfg.minibatch
        n_full = len(ty) // b
        for i in range(n_full):
            sl = slice(i * b, (i + 1) * b)
            self._dispatch(ti[sl], tv[sl], ty[sl], tw[sl])
        rem = len(ty) - n_full * b
        self._tail = (None if rem == 0
                      else [a[n_full * b:] for a in (ti, tv, ty, tw)])
        return n_full

    def _dispatch(self, idx, val, y, w, n_real: int = -1) -> None:
        """Launch one device step ahead of retirement. HOT PATH: the
        jnp.asarray staging and the step call are async dispatches; the
        only blocking is the ring-full backpressure, which is delegated
        to the designated _retire_oldest sync point. `n_real` is the
        non-padding row count (flush's padded tail carries zero-weight
        filler that must not inflate the throughput gauge)."""
        if n_real < 0:
            n_real = len(y)
        while len(self._inflight) >= self._depth:
            self._retire_oldest()
        if self._t_start is None:
            self._t_start = self._clock()
        batch = (jnp.asarray(idx), jnp.asarray(val),
                 jnp.asarray(y), jnp.asarray(w))
        t0 = self._clock()
        self._carry, loss = self._step(self._carry, batch)
        self._inflight.append((loss, n_real, t0))
        self._steps += 1
        self._examples += n_real

    # --------------------------------------------------- designated syncs

    def _retire_oldest(self) -> None:
        """DESIGNATED SYNC POINT: block until the oldest in-flight step
        completes, freeing one ring slot. Loss fetch + metrics publication
        happen here at the metricsEvery cadence — after the block, so the
        fetch costs nothing extra."""
        loss, n, t0 = self._inflight.popleft()
        jax.block_until_ready(loss)
        self._retired += 1
        self._examples_retired += n
        if self._retired % self._metrics_every == 0:
            self._fetch_metrics_host(loss, self._clock() - t0)

    def _fetch_metrics_host(self, loss, step_seconds: float) -> None:
        """DESIGNATED SYNC POINT: the metricsEvery-cadence host fetch.
        `loss` is already retired, so float() is a free host copy."""
        lv = float(loss)
        self._last_loss = lv
        self._loss_history.append((self._retired, lv))
        elapsed = max(self._clock() - (self._t_start or 0.0), 1e-9)
        obsbridge.publish_vw_step_metrics(
            step_seconds=step_seconds,
            examples_per_s=self._examples_retired / elapsed,
            registry=self._registry)

    def flush(self) -> None:
        """COMMIT POINT: dispatch the sub-minibatch tail (padded with
        zero-weight rows, inert through the step) and drain the ring."""
        if self._tail is not None and len(self._tail[2]):
            ti, tv, ty, tw = self._tail
            self._tail = None
            n_real = len(ty)
            ti, tv, ty, tw = pad_examples(ti, tv, ty, tw, self.cfg.minibatch)
            self._dispatch(ti, tv, ty, tw, n_real=n_real)
        while self._inflight:
            self._retire_oldest()

    def state(self) -> VWState:
        """COMMIT POINT: block on the carry and return it as a VWState
        (unpacking the fused table when cfg.fused). Does not drain the
        tail — call flush() first for exactly-submitted semantics."""
        carry = self._carry
        jax.block_until_ready(jax.tree_util.tree_leaves(carry)[0])
        return (unpack_state(self.cfg, carry, self._template)
                if self.cfg.fused else carry)

    def finalize(self) -> Tuple[VWState, Dict]:
        """Flush + drain, then return (state, aux). aux carries the
        sampled loss trajectory (metricsEvery cadence), example/step
        counts, and wall-clock throughput."""
        self.flush()
        state = self.state()
        wall = (max(self._clock() - self._t_start, 1e-9)
                if self._t_start is not None else 0.0)
        eps = self._examples / wall if wall else 0.0
        if self._steps:
            obsbridge.publish_vw_step_metrics(examples_per_s=eps,
                                              registry=self._registry)
        aux = {
            "steps": self._steps,
            "examples": self._examples,
            "wall_s": wall,
            "examples_per_s": eps,
            "losses": np.asarray([v for _, v in self._loss_history],
                                 np.float32),
            "loss_steps": np.asarray([s for s, _ in self._loss_history],
                                     np.int64),
            "last_loss": self._last_loss,
        }
        return state, aux

    # ------------------------------------------------------------- introspection

    @property
    def steps(self) -> int:
        return self._steps

    @property
    def examples(self) -> int:
        return self._examples

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    @property
    def pending_rows(self) -> int:
        return 0 if self._tail is None else len(self._tail[2])
