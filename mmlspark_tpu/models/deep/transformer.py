"""TransformerEncoder — long-context sequence scoring over the device mesh.

The reference's deep path scales by splitting ROWS across executors and
evaluating a broadcast CNTK graph per partition (cntk/CNTKModel.scala:30-140).
Transformer workloads add a second scaling axis the reference never had:
SEQUENCE length. This module is the TPU-native answer — a flax-free encoder
stack whose attention runs either dense on one chip or sequence-parallel over
a mesh axis via ring attention (ops/attention.py: K/V blocks rotating on the
ICI with flash-style streaming softmax), so contexts far beyond one chip's
HBM score exactly, not approximately.

`TransformerEncoderModel` is a pipeline stage with the same transform
contract as DNNModel (padded fixed device batches, feed/fetch columns).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame
from ...core.pipeline import Model
from ...ops.attention import (attention_reference, flash_attention,
                              ring_attention_sharded)


def init_encoder_params(key, num_layers: int, d_model: int, num_heads: int,
                        d_ff: int):
    """Xavier-initialized parameter pytree for an encoder stack."""
    def dense(k, fan_in, fan_out):
        scale = np.sqrt(2.0 / (fan_in + fan_out))
        return {"w": jax.random.normal(k, (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,))}

    layers = []
    for i in range(num_layers):
        ks = jax.random.split(jax.random.fold_in(key, i), 6)
        layers.append({
            "qkv": dense(ks[0], d_model, 3 * d_model),
            "proj": dense(ks[1], d_model, d_model),
            "ff1": dense(ks[2], d_model, d_ff),
            "ff2": dense(ks[3], d_ff, d_model),
            "ln1": {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))},
            "ln2": {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))},
        })
    return {"layers": layers}


def _layer_norm(x, p):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * p["g"] + p["b"]


def _apply(p, x):
    return x @ p["w"] + p["b"]


def encoder_forward(params, x: jax.Array, num_heads: int,
                    causal: bool = False,
                    axis_name: Optional[str] = None,
                    attention_impl: str = "flash") -> jax.Array:
    """Pre-LN encoder stack. x: [B, S, D] (shard-local S when axis_name is
    set — every non-attention op is position-wise, so only attention needs
    the ring). Single-device attention uses the fused Pallas flash kernel
    (no [S, S] score matrix in HBM); attention_impl="reference" keeps the
    dense XLA path for cross-checks."""
    b, s, d = x.shape
    hd = d // num_heads
    for lp in params["layers"]:
        h = _layer_norm(x, lp["ln1"])
        qkv = _apply(lp["qkv"], h).reshape(b, s, 3, num_heads, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if axis_name is None:
            if attention_impl == "flash":
                att = flash_attention(q, k, v, causal=causal)
            else:
                att = attention_reference(q, k, v, causal=causal)
        else:
            att = ring_attention_sharded(q, k, v, axis_name, causal=causal)
        x = x + _apply(lp["proj"], att.reshape(b, s, d))
        h = _layer_norm(x, lp["ln2"])
        x = x + _apply(lp["ff2"], jax.nn.gelu(_apply(lp["ff1"], h)))
    return x


class TransformerEncoderModel(Model, _p.HasInputCol, _p.HasOutputCol):
    """Sequence scorer: inputCol holds [S, D] float sequences (stacked
    [N, S, D] or object column); outputCol receives the encoded [S, D]
    sequence (or its mean-pooled [D] vector with pool='mean').

    numTasks > 1 shards the SEQUENCE axis over the mesh and runs ring
    attention — the long-context path. Weights live host-side in a pytree
    (`params`), loadable from the downloader/zoo like DNNModel weights.
    """

    numHeads = _p.Param("numHeads", "attention heads", 4, int)
    causal = _p.Param("causal", "causal (autoregressive) masking", False)
    pool = _p.Param("pool", "output pooling: none | mean", "none")
    numTasks = _p.Param("numTasks",
                        "sequence-parallel shards; 0/1 = single device", 0,
                        int)
    weights = _p.Param("weights", "encoder parameter pytree", None,
                       complex=True)

    def __init__(self, **kw):
        super().__init__()
        kw.setdefault("inputCol", "sequence")
        kw.setdefault("outputCol", "encoded")
        self._set(**kw)

    def _forward(self, x: jax.Array) -> jax.Array:
        from ...parallel import mesh as meshlib
        p = self.get("weights")
        if p is None:
            raise ValueError("TransformerEncoderModel needs `weights` "
                             "(init_encoder_params or a loaded checkpoint)")
        nh = self.get("numHeads")
        causal = self.get("causal")
        ndev = self.get("numTasks")
        if ndev and ndev > 1:
            from jax.sharding import PartitionSpec as P
            mesh = meshlib.get_mesh(ndev)
            axis = meshlib.DATA_AXIS
            fn = jax.shard_map(
                partial(encoder_forward, num_heads=nh, causal=causal,
                        axis_name=axis),
                mesh=mesh, in_specs=(P(), P(None, axis, None)),
                out_specs=P(None, axis, None), check_vma=False)
            return jax.jit(fn)(p, x)
        return jax.jit(partial(encoder_forward, num_heads=nh,
                               causal=causal))(p, x)

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("inputCol")]
        if col.dtype == object:
            x = jnp.asarray(np.stack([np.asarray(v, np.float32)
                                      for v in col]))
        else:
            x = jnp.asarray(np.asarray(col, np.float32))
        out = np.asarray(self._forward(x))
        if self.get("pool") == "mean":
            out = out.mean(axis=1)
            return df.with_column(self.get("outputCol"), out)
        obj = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            obj[i] = out[i]
        return df.with_column(self.get("outputCol"), obj)
