"""TransformerEncoder — long-context sequence scoring over the device mesh.

The reference's deep path scales by splitting ROWS across executors and
evaluating a broadcast CNTK graph per partition (cntk/CNTKModel.scala:30-140).
Transformer workloads add a second scaling axis the reference never had:
SEQUENCE length. This module is the TPU-native answer — a flax-free encoder
stack whose attention runs either dense on one chip or sequence-parallel over
a mesh axis via ring attention (ops/attention.py: K/V blocks rotating on the
ICI with flash-style streaming softmax), so contexts far beyond one chip's
HBM score exactly, not approximately.

`TransformerEncoderModel` is a pipeline stage with the same transform
contract as DNNModel (padded fixed device batches, feed/fetch columns).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ...parallel.mesh import shard_map as _shard_map
import numpy as np
from jax.flatten_util import ravel_pytree

from ...core import params as _p
from ...core.dataframe import DataFrame
from ...core.pipeline import Estimator, Model
from ...ops.attention import (attention_reference, flash_attention,
                              ring_attention_sharded,
                              ulysses_attention_sharded)


def init_encoder_params(key, num_layers: int, d_model: int, num_heads: int,
                        d_ff: int):
    """Xavier-initialized parameter pytree for an encoder stack."""
    def dense(k, fan_in, fan_out):
        scale = np.sqrt(2.0 / (fan_in + fan_out))
        return {"w": jax.random.normal(k, (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,))}

    layers = []
    for i in range(num_layers):
        ks = jax.random.split(jax.random.fold_in(key, i), 6)
        layers.append({
            "qkv": dense(ks[0], d_model, 3 * d_model),
            "proj": dense(ks[1], d_model, d_model),
            "ff1": dense(ks[2], d_model, d_ff),
            "ff2": dense(ks[3], d_ff, d_model),
            "ln1": {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))},
            "ln2": {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))},
        })
    return {"layers": layers}


def _layer_norm(x, p):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * p["g"] + p["b"]


def _apply(p, x):
    return x @ p["w"] + p["b"]


def sinusoidal_positions(start: jax.Array, s: int, d: int) -> jax.Array:
    """[s, d] sinusoidal positional encodings for GLOBAL positions
    [start, start+s) — `start` may be traced, so a sequence-parallel shard
    encodes its own slice of the global position space."""
    pos = start + jnp.arange(s)[:, None].astype(jnp.float32)
    dim = jnp.arange(0, d, 2)[None, :].astype(jnp.float32)
    angle = pos / jnp.power(10000.0, dim / d)
    pe = jnp.zeros((s, d))
    pe = pe.at[:, 0::2].set(jnp.sin(angle))
    pe = pe.at[:, 1::2].set(jnp.cos(angle[:, : d // 2]))
    return pe


def attention_sublayer(x, lp, num_heads: int, causal: bool = False,
                       axis_name: Optional[str] = None,
                       attention_impl: str = "flash"):
    """Pre-LN attention + residual — THE single attention definition
    shared by encoder_layer, the pipeline stage scan
    (models/deep/pipeline.py) and the MoE encoder
    (models/deep/moe_encoder.py), so their exactness contract cannot
    drift."""
    b, s, d = x.shape
    hd = d // num_heads
    h = _layer_norm(x, lp["ln1"])
    qkv = _apply(lp["qkv"], h).reshape(b, s, 3, num_heads, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if axis_name is None:
        if attention_impl == "flash":
            att = flash_attention(q, k, v, causal=causal)
        else:
            att = attention_reference(q, k, v, causal=causal)
    elif attention_impl == "ulysses":
        att = ulysses_attention_sharded(q, k, v, axis_name, causal=causal)
    else:
        att = ring_attention_sharded(q, k, v, axis_name, causal=causal)
    return x + _apply(lp["proj"], att.reshape(b, s, d))


def encoder_layer(x, lp, num_heads: int, causal: bool = False,
                  axis_name: Optional[str] = None,
                  attention_impl: str = "flash"):
    """One pre-LN encoder layer: shared attention sublayer + dense FFN."""
    x = attention_sublayer(x, lp, num_heads, causal, axis_name,
                           attention_impl)
    h = _layer_norm(x, lp["ln2"])
    return x + _apply(lp["ff2"], jax.nn.gelu(_apply(lp["ff1"], h)))


def encoder_forward(params, x: jax.Array, num_heads: int,
                    causal: bool = False,
                    axis_name: Optional[str] = None,
                    attention_impl: str = "flash",
                    positional: bool = False,
                    remat: bool = False) -> jax.Array:
    """Pre-LN encoder stack. x: [B, S, D] (shard-local S when axis_name is
    set — every non-attention op is position-wise, so only attention needs
    a cross-shard strategy). Single-device attention uses the fused Pallas
    flash kernel (no [S, S] score matrix in HBM); attention_impl=
    "reference" keeps the dense XLA path for cross-checks. Sharded
    (axis_name set): attention_impl="ulysses" picks the all-to-all
    head-sharding strategy (needs num_heads divisible by the axis size),
    anything else the ppermute ring. positional=True adds sinusoidal
    encodings — under sequence parallelism each shard offsets by its
    GLOBAL start position, so sharded and dense runs encode identically."""
    b, s, d = x.shape
    if positional:
        if axis_name is None:
            start = jnp.int32(0)
        else:
            start = jax.lax.axis_index(axis_name) * s
        x = x + sinusoidal_positions(start.astype(jnp.float32), s,
                                     d)[None, :, :]

    def layer(x, lp):
        return encoder_layer(x, lp, num_heads, causal=causal,
                             axis_name=axis_name,
                             attention_impl=attention_impl)

    if remat:
        # rematerialisation: drop per-layer activations on the forward pass
        # and recompute them in the backward — activation memory falls from
        # O(layers) to O(1) residual streams (+ the recomputed layer),
        # trading ~1/3 more FLOPs. The long-context lever: HBM, not MXU, is
        # the training-batch ceiling.
        layer = jax.checkpoint(layer)
    for lp in params["layers"]:
        x = layer(x, lp)
    return x


def _stack_sequences(col) -> np.ndarray:
    """Object column of [S, D] arrays (or an already-stacked [N, S, D]
    column) -> float32 [N, S, D]."""
    if col.dtype == object:
        return np.stack([np.asarray(v, np.float32) for v in col])
    return np.asarray(col, np.float32)


def init_head_params(key, d_model: int, num_out: int):
    scale = np.sqrt(2.0 / (d_model + num_out))
    return {"w": jax.random.normal(key, (d_model, num_out)) * scale,
            "b": jnp.zeros((num_out,))}


def _shard_layer(lp, tp_rank, tp, num_heads):
    """Megatron-style tensor-parallel slice of one encoder layer: qkv/ff1
    column-parallel (output dim split over the model axis, head-aligned for
    qkv), proj/ff2 row-parallel (input dim split); LN replicated."""
    d = lp["qkv"]["w"].shape[0]
    hd = d // num_heads
    h_loc = num_heads // tp
    # qkv.w [D, 3D] column order is (3, H, hd) after the forward reshape —
    # slice the H dim so each shard owns whole heads
    qkv_w = lp["qkv"]["w"].reshape(d, 3, num_heads, hd)[
        :, :, tp_rank * h_loc:(tp_rank + 1) * h_loc]
    qkv_b = lp["qkv"]["b"].reshape(3, num_heads, hd)[
        :, tp_rank * h_loc:(tp_rank + 1) * h_loc]
    dloc = h_loc * hd
    f = lp["ff1"]["w"].shape[1]
    if f % tp:
        raise ValueError(
            f"feed-forward width {f} must divide evenly over the model "
            f"axis ({tp} shards) — a silent f//tp truncation would drop "
            f"hidden units")
    floc = f // tp
    return {
        "qkv": {"w": qkv_w.reshape(d, 3 * dloc),
                "b": qkv_b.reshape(3 * dloc)},
        # row-parallel biases stay REPLICATED (full value on every shard,
        # added OUTSIDE the psum): a b/tp-per-shard split would receive the
        # full bias gradient on each fraction and amplify the update by tp
        "proj": {"w": lp["proj"]["w"][tp_rank * dloc:(tp_rank + 1) * dloc],
                 "b": lp["proj"]["b"]},
        "ff1": {"w": lp["ff1"]["w"][:, tp_rank * floc:(tp_rank + 1) * floc],
                "b": lp["ff1"]["b"][tp_rank * floc:(tp_rank + 1) * floc]},
        "ff2": {"w": lp["ff2"]["w"][tp_rank * floc:(tp_rank + 1) * floc],
                "b": lp["ff2"]["b"]},
        "ln1": lp["ln1"], "ln2": lp["ln2"],
    }


def shard_encoder_params(params, tp_rank: int, tp: int, num_heads: int):
    return {"layers": [_shard_layer(lp, tp_rank, tp, num_heads)
                       for lp in params["layers"]]}


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _copy_to_model_shards(x, axis):
    """Megatron's 'f' operator: identity forward, psum backward. Placed at
    every column-parallel branch INPUT — each shard's backward only sees its
    own branch's cotangent, so the residual stream (and everything upstream:
    layer norms, earlier layers) needs the branch contributions summed over
    the model axis to receive the full gradient."""
    return x


def _copy_fwd(x, axis):
    return x, None


def _copy_bwd(axis, _, g):
    return (jax.lax.psum(g, axis),)


_copy_to_model_shards.defvjp(_copy_fwd, _copy_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _reduce_from_model_shards(x, axis):
    """Megatron's 'g' operator: psum forward, identity backward (the
    cotangent of a sum is replicated to every contributor)."""
    return jax.lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _reduce_bwd(axis, _, g):
    return (g,)


_reduce_from_model_shards.defvjp(_reduce_fwd, _reduce_bwd)


def _encoder_forward_tp(params, x, num_heads_local, model_axis,
                        causal=False, remat=False):
    """Encoder forward on tensor-parallel layer shards: attention over the
    LOCAL heads and MLP over the LOCAL hidden slice, with ONE psum over the
    model axis per residual branch (the Megatron pattern: column-parallel
    then row-parallel matmuls, communication only at the row-parallel
    output, conjugate f/g operators making the per-shard backward exact).
    Everything else is replicated across the model axis. remat=True
    recomputes each layer in the backward pass (jax.checkpoint) — the
    activation-memory lever for deep stacks."""
    b, s, d = x.shape

    def layer(x, lp):
        h = _copy_to_model_shards(_layer_norm(x, lp["ln1"]), model_axis)
        dloc = lp["qkv"]["w"].shape[1] // 3
        hd = dloc // num_heads_local
        qkv = _apply(lp["qkv"], h).reshape(b, s, 3, num_heads_local, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        att = attention_reference(q, k, v, causal=causal)
        part = att.reshape(b, s, dloc) @ lp["proj"]["w"]
        x = x + _reduce_from_model_shards(part, model_axis) + lp["proj"]["b"]
        h = _copy_to_model_shards(_layer_norm(x, lp["ln2"]), model_axis)
        part = jax.nn.gelu(_apply(lp["ff1"], h)) @ lp["ff2"]["w"]
        return x + _reduce_from_model_shards(part, model_axis) + lp["ff2"]["b"]

    if remat:
        layer = jax.checkpoint(layer)
    for lp in params["layers"]:
        x = layer(x, lp)
    return x


def make_tp_dp_train_step(mesh, num_heads: int, learning_rate: float,
                          num_classes: int, causal: bool = False,
                          data_axis: Optional[str] = None,
                          model_axis: Optional[str] = None,
                          zero1: bool = False,
                          remat: bool = False,
                          compute_dtype=None):
    """One distributed transformer training step over a 2-D (data, model)
    mesh: batch data-parallel, layers tensor-parallel (Megatron split),
    Adam, softmax cross-entropy on the mean-pooled encoding.

    No reference analogue — the reference's deep path is inference-only
    (cntk/CNTKModel.scala evaluates a broadcast frozen graph). Training is
    TPU-native surface: jax.grad INSIDE shard_map differentiates straight
    through the tensor-parallel psums (their transpose is the correct
    replicated cotangent), and gradients psum over the data axis only —
    tensor-parallel shards own disjoint parameter slices, and replicated
    LN/head parameters see identical activations on every model shard, so
    their gradients already agree across the model axis.

    compute_dtype=jnp.bfloat16 runs the forward/backward in bf16 (the
    MXU-native dtype — 2x the matmul rate and half the activation HBM of
    f32 on TPU) while parameters, gradients-as-accumulated, and optimizer
    state stay f32 (mixed-precision master-weight discipline: the cast
    happens inside the loss, so jax.grad accumulates cotangents back into
    f32 leaves). Loss curves track f32 to bf16's ~3 decimal digits.

    zero1=True shards the Adam state over the DATA axis (ZeRO stage 1 /
    the scaling-book optimizer-sharding recipe): the data-axis psum of
    gradients becomes a psum_scatter (reduce_scatter), each dp rank runs
    Adam on its 1/dp slice of the flattened parameter vector, and one
    tiled all_gather rebuilds the replicated parameters — identical math
    to the replicated optimizer (regression-gated), with per-device
    optimizer memory cut by the data-axis size and the psum's O(|g|)
    traffic replaced by reduce_scatter + all_gather of the same total
    volume.

    Returns (step, shard_params) where
      step(local_params, opt_state, x_local, y_local) is shard_map'd over
      the mesh and jitted; call it with per-device-sharded arrays.
    """
    import optax
    from ...parallel import mesh as meshlib
    data_axis = data_axis or meshlib.DATA_AXIS
    model_axis = model_axis or meshlib.MODEL_AXIS
    tx = optax.adam(learning_rate)
    from jax.sharding import PartitionSpec as P
    tp = mesh.shape[model_axis]
    n_dp = mesh.shape[data_axis]
    if num_heads % tp:
        raise ValueError(
            f"num_heads {num_heads} must divide evenly over the model axis "
            f"({tp} shards) — tensor-parallel slices own whole heads")
    nh_loc = num_heads // tp

    def loss_fn(params, x, y):
        enc_params = params["encoder"]
        if compute_dtype is not None:
            # ONLY the encoder compute drops precision; the head (and the
            # loss math) stays f32, and the master params are untouched —
            # jax.grad accumulates the bf16 cotangents back into f32 leaves
            # through the cast's transpose
            enc_params = jax.tree_util.tree_map(
                lambda a: a.astype(compute_dtype), enc_params)
            x = x.astype(compute_dtype)
        enc = _encoder_forward_tp(enc_params, x, nh_loc, model_axis,
                                  causal, remat=remat)
        pooled = enc.mean(axis=1).astype(jnp.float32)
        logits = pooled @ params["head"]["w"] + params["head"]["b"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        onehot = jax.nn.one_hot(y, num_classes)
        # per-shard SUM: the data-axis psum then divides by the global
        # batch so the result equals the full-batch mean loss
        return -jnp.sum(onehot * logp)

    def peeled_loss_and_grads(params, x, y):
        # params arrive with a size-1 leading model-shard axis (the
        # host-side stack sharded over the model axis) — peel it for
        # compute. Shared by both optimizer paths so the loss/gradient
        # semantics cannot drift between them.
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        denom = x.shape[0] * n_dp
        loss = jax.lax.psum(loss, data_axis) / denom
        return params, grads, loss, denom

    def step(params, opt_state, x, y):
        params, grads, loss, denom = peeled_loss_and_grads(params, x, y)
        opt_state = jax.tree_util.tree_map(lambda a: a[0], opt_state)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, data_axis) / denom, grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        lift = lambda a: a[None]
        return (jax.tree_util.tree_map(lift, params),
                jax.tree_util.tree_map(lift, opt_state), loss)

    def step_zero1(params, opt_state, x, y):
        # ZeRO-1: optimizer state lives only on the dp rank that owns the
        # slice. The SAME `tx` drives the update, applied to the owned
        # (gradient shard, parameter shard) pair and finished with
        # optax.apply_updates — so params-dependent transforms (weight
        # decay) and dtype handling behave exactly as on the replicated
        # path; only WHERE the state lives differs.
        params, grads, loss, _denom = peeled_loss_and_grads(params, x, y)
        opt_state = jax.tree_util.tree_map(lambda a: a[0, 0], opt_state)
        flat_g, _ = ravel_pytree(grads)
        size = flat_g.shape[0]
        pad = (-size) % n_dp
        flat_g = jnp.pad(flat_g, (0, pad)) / _denom
        # reduce_scatter: rank d receives the dp-sum of chunk d only
        g_shard = jax.lax.psum_scatter(flat_g, data_axis,
                                       scatter_dimension=0, tiled=True)
        flat_p, unravel = ravel_pytree(params)
        chunk = g_shard.shape[0]
        rank = jax.lax.axis_index(data_axis)
        p_shard = jax.lax.dynamic_slice_in_dim(
            jnp.pad(flat_p, (0, pad)), rank * chunk, chunk)
        upd_shard, opt_state = tx.update(g_shard, opt_state, p_shard)
        p_shard = optax.apply_updates(p_shard, upd_shard)
        flat_p = jax.lax.all_gather(p_shard, data_axis, tiled=True)[:size]
        params = unravel(flat_p)
        lift = lambda a: a[None]
        lift2 = lambda a: a[None, None]
        return (jax.tree_util.tree_map(lift, params),
                jax.tree_util.tree_map(lift2, opt_state), loss)

    if zero1:
        opt_spec = P(model_axis, data_axis)
        sharded = _shard_map(
            step_zero1, mesh=mesh,
            in_specs=(P(model_axis), opt_spec,
                      P(data_axis), P(data_axis)),
            out_specs=(P(model_axis), opt_spec, P()),
            check_vma=False)
    else:
        sharded = _shard_map(
            step, mesh=mesh,
            in_specs=(P(model_axis), P(model_axis),
                      P(data_axis), P(data_axis)),
            out_specs=(P(model_axis), P(model_axis), P()),
            check_vma=False)

    def shard_params(full_params, head):
        """Host-side split of full parameters (+ fresh Adam state) into the
        per-model-shard stacked layout the step consumes (leading axis =
        model shards; zero1 also chunks the flat optimizer state over the
        data axis: [tp, dp, chunk])."""
        shards = [
            {"encoder": shard_encoder_params(full_params, r, tp, num_heads),
             "head": head}
            for r in range(tp)]
        stack = lambda *xs: jnp.stack(xs)
        stacked = jax.tree_util.tree_map(stack, *shards)
        if not zero1:
            opt_shards = [tx.init(s) for s in shards]
            return stacked, jax.tree_util.tree_map(stack, *opt_shards)
        size = ravel_pytree(shards[0])[0].shape[0]
        chunk = -(-size // n_dp)
        opt0 = tx.init(jnp.zeros((chunk,), jnp.float32))
        tile = lambda a: jnp.broadcast_to(
            jnp.asarray(a)[None, None], (tp, n_dp) + jnp.shape(a))
        return stacked, jax.tree_util.tree_map(tile, opt0)

    return jax.jit(sharded), shard_params


def unshard_encoder_params(stacked_encoder, num_heads: int):
    """Inverse of shard_encoder_params on the stacked (leading axis = model
    shards) layout: reassemble the full encoder parameter pytree."""
    layers = []
    n_layers = len(stacked_encoder["layers"])
    for i in range(n_layers):
        lp = stacked_encoder["layers"][i]
        tp, d, w3 = lp["qkv"]["w"].shape
        h_loc = num_heads // tp
        hd = w3 // 3 // h_loc
        qkv_w = jnp.concatenate(
            [np.asarray(lp["qkv"]["w"][r]).reshape(d, 3, h_loc, hd)
             for r in range(tp)], axis=2).reshape(d, 3 * num_heads * hd)
        qkv_b = jnp.concatenate(
            [np.asarray(lp["qkv"]["b"][r]).reshape(3, h_loc, hd)
             for r in range(tp)], axis=1).reshape(3 * num_heads * hd)
        layers.append({
            "qkv": {"w": qkv_w, "b": qkv_b},
            "proj": {"w": jnp.concatenate(list(lp["proj"]["w"]), axis=0),
                     "b": lp["proj"]["b"][0]},
            "ff1": {"w": jnp.concatenate(list(lp["ff1"]["w"]), axis=1),
                    "b": jnp.concatenate(list(lp["ff1"]["b"]), axis=0)},
            "ff2": {"w": jnp.concatenate(list(lp["ff2"]["w"]), axis=0),
                    "b": lp["ff2"]["b"][0]},
            "ln1": {"g": lp["ln1"]["g"][0], "b": lp["ln1"]["b"][0]},
            "ln2": {"g": lp["ln2"]["g"][0], "b": lp["ln2"]["b"][0]},
        })
    return {"layers": layers}


def make_single_train_step(num_heads: int, learning_rate: float,
                           num_classes: int, causal: bool = False):
    """Unsharded reference trainer (same loss/optimizer as the tp x dp
    step) — the numerical anchor the distributed step is tested against."""
    import optax
    tx = optax.adam(learning_rate)

    def loss_fn(params, x, y):
        enc = encoder_forward(params["encoder"], x, num_heads, causal,
                              attention_impl="reference")
        pooled = enc.mean(axis=1)
        logits = pooled @ params["head"]["w"] + params["head"]["b"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.sum(jax.nn.one_hot(y, num_classes) * logp,
                                 axis=-1))

    @jax.jit
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def init_opt(params):
        return tx.init(params)

    return step, init_opt


class TransformerEncoderModel(Model, _p.HasInputCol, _p.HasOutputCol):
    """Sequence scorer: inputCol holds [S, D] float sequences (stacked
    [N, S, D] or object column); outputCol receives the encoded [S, D]
    sequence (or its mean-pooled [D] vector with pool='mean').

    numTasks > 1 shards the SEQUENCE axis over the mesh — the long-context
    path — with `sequenceAttention` choosing the cross-shard strategy:
    'ring' (ppermute K/V rotation, any head count) or 'ulysses'
    (all-to-all head sharding, heads divisible by the axis). Weights live
    host-side in a pytree (`params`), loadable from the downloader/zoo
    like DNNModel weights.
    """

    numHeads = _p.Param("numHeads", "attention heads", 4, int)
    causal = _p.Param("causal", "causal (autoregressive) masking", False)
    sequenceAttention = _p.Param(
        "sequenceAttention",
        "sequence-parallel attention strategy: ring | ulysses", "ring")
    positionalEncoding = _p.Param(
        "positionalEncoding", "add sinusoidal positional encodings (global "
        "positions — sequence-parallel shards offset by their slice start)",
        False)
    pool = _p.Param("pool", "output pooling: none | mean", "none")
    numTasks = _p.Param("numTasks",
                        "sequence-parallel shards; 0/1 = single device", 0,
                        int)
    weights = _p.Param("weights", "encoder parameter pytree", None,
                       complex=True)

    def __init__(self, **kw):
        super().__init__()
        kw.setdefault("inputCol", "sequence")
        kw.setdefault("outputCol", "encoded")
        self._set(**kw)

    def _compiled(self):
        """Acquire the jitted forward from the shared cached_jit registry,
        keyed on the full static config — rebuilding the shard_map/jit
        closure every call would retrace + recompile on each transform,
        and a per-instance cache would still recompile identical configs
        across instances (round-11 churn fix)."""
        from ...compile.cache import cached_jit
        from ...parallel import mesh as meshlib
        nh = self.get("numHeads")
        causal = self.get("causal")
        ndev = self.get("numTasks")
        pos = self.get("positionalEncoding")
        seq_attn = self.get("sequenceAttention")
        if seq_attn not in ("ring", "ulysses"):
            raise ValueError(f"sequenceAttention must be 'ring' or "
                             f"'ulysses', got {seq_attn!r}")
        key = ("transformer_encoder_fwd", nh, causal, ndev, pos, seq_attn)
        if ndev and ndev > 1:
            from jax.sharding import PartitionSpec as P
            mesh = meshlib.get_mesh(ndev)
            axis = meshlib.DATA_AXIS
            fn = _shard_map(
                partial(encoder_forward, num_heads=nh, causal=causal,
                        axis_name=axis, positional=pos,
                        attention_impl=seq_attn),
                mesh=mesh, in_specs=(P(), P(None, axis, None)),
                out_specs=P(None, axis, None), check_vma=False)
        else:
            fn = partial(encoder_forward, num_heads=nh, causal=causal,
                         positional=pos)
        return cached_jit(fn, key=key, name="transformer_encoder_fwd")

    def _forward(self, x: jax.Array) -> jax.Array:
        p = self.get("weights")
        if p is None:
            raise ValueError("TransformerEncoderModel needs `weights` "
                             "(init_encoder_params or a loaded checkpoint)")
        return self._compiled()(p, x)

    def transform(self, df: DataFrame) -> DataFrame:
        x = jnp.asarray(_stack_sequences(df[self.get("inputCol")]))
        out = np.asarray(self._forward(x))
        if self.get("pool") == "mean":
            out = out.mean(axis=1)
            return df.with_column(self.get("outputCol"), out)
        obj = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            obj[i] = out[i]
        return df.with_column(self.get("outputCol"), obj)


class TransformerEncoderClassifier(Estimator, _p.HasInputCol,
                                   _p.HasLabelCol):
    """Train a transformer-encoder classifier over a 2-D (data x model)
    device mesh: batches data-parallel, layers tensor-parallel
    (make_tp_dp_train_step), softmax cross-entropy on the mean-pooled
    encoding, Adam.

    Beyond-reference surface: the reference's deep-learning path only
    EVALUATES broadcast frozen graphs (cntk/CNTKModel.scala:30-140,
    SURVEY §2.1) — its training story stops at classical models. This is
    the TPU-native extension: the same Estimator/Model pipeline contract,
    with the distributed step exercised by __graft_entry__.dryrun_multichip
    on the (data, model) mesh.
    """

    numLayers = _p.Param("numLayers", "encoder layers", 2, int)
    dModel = _p.Param("dModel", "model width", 32, int)
    numHeads = _p.Param("numHeads", "attention heads", 4, int)
    dFF = _p.Param("dFF", "feed-forward width", 64, int)
    numClasses = _p.Param("numClasses", "output classes (0 = infer)", 0, int)
    learningRate = _p.Param("learningRate", "Adam learning rate", 1e-3,
                            float)
    epochs = _p.Param("epochs", "training epochs", 5, int)
    batchSize = _p.Param("batchSize", "global batch size", 32, int)
    causal = _p.Param("causal", "causal masking", False)
    dataParallel = _p.Param("dataParallel",
                            "data-parallel mesh extent; 0 (default) = auto "
                            "— all visible devices for the plain tensor "
                            "strategy when they divide the batch size "
                            "(psum-mean gradients match the single-device "
                            "full-batch step to fp reassociation), one "
                            "device otherwise; 1 = single device",
                            0, int)
    modelParallel = _p.Param("modelParallel",
                             "model-axis mesh extent: tensor-parallel ranks "
                             "(strategy='tensor') or pipeline stages "
                             "(strategy='pipeline')", 1, int)
    strategy = _p.Param(
        "strategy",
        "distributed strategy: 'tensor' (Megatron column/row split per "
        "layer over a data x model mesh, make_tp_dp_train_step), "
        "'pipeline' (GPipe microbatch schedule, layers split into "
        "contiguous stages over the model axis, make_pp_dp_train_step), "
        "'sequence' (long-context regime: the SEQUENCE axis sharded "
        "over modelParallel devices via ring attention, parameters "
        "replicated, make_sp_train_step; dataParallel must be 0/1), or "
        "'moe' (Switch-MoE encoder: every layer's FFN replaced by "
        "numExperts top-1-routed experts sharded over the model axis, "
        "tokens all_to_all-dispatched, make_moe_ep_dp_train_step)",
        "tensor")
    numExperts = _p.Param(
        "numExperts",
        "expert count for strategy='moe' (must divide over modelParallel)",
        8, int)
    capacityFactor = _p.Param(
        "capacityFactor",
        "MoE expert capacity factor (tokens per expert bucket = "
        "capacity_factor * tokens/experts)", 2.0, float)
    numMicrobatches = _p.Param(
        "numMicrobatches",
        "GPipe microbatches per step (strategy='pipeline'); batch size "
        "rounds to a multiple of dataParallel * numMicrobatches", 2, int)
    zero1 = _p.Param(
        "zero1",
        "ZeRO-1 optimizer-state sharding over the data axis "
        "(strategy='tensor' only): reduce_scatter grads, Adam on the owned "
        "1/dataParallel flat chunk, all_gather updates — optimizer memory "
        "divided by dataParallel at identical losses", False, bool)
    seed = _p.Param("seed", "init/shuffle seed", 0, int)
    checkpointDir = _p.Param(
        "checkpointDir",
        "epoch-granular resumable training: after every epoch the sharded "
        "(params, optimizer) state is written via save_train_state "
        "(models/deep/checkpoint.py), and a fit() finding checkpoints in "
        "the directory resumes from the latest epoch — shuffles are "
        "per-epoch seeded, so resume replays the uninterrupted run "
        "exactly. Checkpoints are kept on completion (epoch history); "
        "start a fresh fit with a fresh directory. A resume REQUIRES the "
        "same mesh layout (a clear mesh-naming error otherwise); to "
        "continue at a different device count restore through "
        "models/deep/checkpoint.restore_train_state_resharded", None)
    checkpointKeepLast = _p.Param(
        "checkpointKeepLast",
        "keep-last-K retention for checkpointDir epoch dirs (0 = keep "
        "every epoch, the legacy history behavior). Crash recovery only "
        "needs the newest snapshot or two; long fits should bound the "
        "directory", 0, int)

    def __init__(self, **kw):
        super().__init__()
        kw.setdefault("inputCol", "sequence")
        kw.setdefault("labelCol", "label")
        self._set(**kw)

    def _sequences(self, df: DataFrame) -> np.ndarray:
        return _stack_sequences(df[self.get("inputCol")])

    def _fit(self, df: DataFrame) -> "TransformerClassificationModel":
        from ...parallel import mesh as meshlib
        x = self._sequences(df)
        y = np.asarray(df[self.get("labelCol")]).astype(np.int32)
        n, s, d = x.shape
        nc = self.get("numClasses") or int(y.max()) + 1
        nh = self.get("numHeads")
        key = jax.random.PRNGKey(self.get("seed"))
        k_enc, k_head = jax.random.split(key)
        if d != self.get("dModel"):
            raise ValueError(
                f"input feature width {d} != dModel {self.get('dModel')}")
        # the moe strategy builds its own parameter tree — don't
        # materialize a dense stack it would immediately discard
        params = (None if self.get("strategy") == "moe"
                  else init_encoder_params(k_enc, self.get("numLayers"),
                                           self.get("dModel"), nh,
                                           self.get("dFF")))
        head = init_head_params(k_head, d, nc)

        dp = self.get("dataParallel") or 1
        tp = self.get("modelParallel") or 1
        if (not self.get("dataParallel") and tp <= 1
                and self.get("strategy") == "tensor"
                and not self.get("zero1")):
            # mesh by default: with >1 visible device and a batch the
            # devices divide evenly, the plain tensor strategy shards the
            # batch data-parallel automatically (per-shard sum + psum /
            # global batch == the full-batch mean gradient, so this is
            # the same training up to fp reassociation). Explicit
            # dataParallel, model-parallel strategies and zero1 keep
            # their requested meshes — auto never changes an explicit
            # distributed layout, and zero1's error surface stays intact.
            ndev = meshlib.device_count()
            if ndev > 1 and self.get("batchSize") % ndev == 0 \
                    and n >= ndev:
                dp = ndev
        self._dp_resolved = dp
        # cap at the dataset size (and round to the data-parallel extent) so
        # small datasets still train instead of silently skipping every step
        bs = min(max(self.get("batchSize"), dp), n)
        bs -= bs % dp
        if bs < dp:
            raise ValueError(
                f"{n} rows cannot fill a {dp}-way data-parallel batch")
        lr = self.get("learningRate")
        ckdir = self.get("checkpointDir")

        def _epoch_order(ep: int) -> np.ndarray:
            # per-epoch seeded shuffle: resume at epoch E replays the SAME
            # batch sequence the uninterrupted run would have used
            return np.random.default_rng(
                [self.get("seed"), ep]).permutation(n)

        def _train_loop(step, p_st, o_st, bs_, to_templates=None):
            """Shared resume + epoch loop: restore from ckdir when present
            (to_templates re-places state for the sharded layouts), then
            run the remaining epochs, checkpointing after each."""
            start = 0
            if ckdir:
                from .checkpoint import latest_step, restore_train_state
                ls = latest_step(ckdir)
                if ls is not None:
                    tp_, to_ = ((p_st, o_st) if to_templates is None
                                else to_templates(p_st, o_st))
                    p_st, o_st = restore_train_state(ckdir, tp_, to_,
                                                     step=ls)
                    start = ls
            for ep in range(start, self.get("epochs")):
                order = _epoch_order(ep)
                for lo in range(0, n - bs_ + 1, bs_):
                    idx = order[lo:lo + bs_]
                    p_st, o_st, _ = step(p_st, o_st, jnp.asarray(x[idx]),
                                         jnp.asarray(y[idx]))
                if ckdir:
                    from .checkpoint import save_train_state
                    keep = self.get("checkpointKeepLast") or None
                    save_train_state(ckdir, p_st, o_st, step=ep + 1,
                                     keep_last=keep)
            return p_st, o_st

        strategy = self.get("strategy")
        if strategy not in ("tensor", "pipeline", "sequence", "moe"):
            raise ValueError(f"strategy must be 'tensor', 'pipeline', "
                             f"'sequence' or 'moe', got {strategy!r}")
        # validated before the strategy dispatch so EVERY path — sequence,
        # single-device included — rejects an unusable zero1 instead of
        # silently ignoring it
        if self.get("zero1"):
            if strategy != "tensor":
                raise ValueError(
                    "zero1 requires strategy='tensor' (the pipeline step "
                    "keeps its optimizer replicated over data)")
            if dp * tp <= 1:
                raise ValueError(
                    "zero1 shards optimizer state over a device mesh; it "
                    "needs dataParallel*modelParallel > 1")
        if strategy == "sequence" and tp > 1:
            if dp > 1:
                raise ValueError(
                    "strategy='sequence' shards the sequence over "
                    "modelParallel devices with replicated parameters; "
                    "set dataParallel=0/1")
            if s % tp:
                raise ValueError(
                    f"sequence length {s} must divide over {tp} shards")
            mesh1 = meshlib.get_mesh(tp)
            step, init_opt = make_sp_train_step(
                mesh1, nh, lr, nc, self.get("causal"))
            p = {"encoder": params, "head": head}
            o = init_opt(p)

            def _to_seq_templates(p_st, o_st):
                # replicate onto the 1-D mesh (orbax restores committed
                # arrays; shard_map needs the mesh's device set)
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as _P
                spec = NamedSharding(mesh1, _P())
                put = lambda a: jax.device_put(a, spec)
                return (jax.tree_util.tree_map(put, p_st),
                        jax.tree_util.tree_map(put, o_st))

            p, o = _train_loop(step, p, o, bs,
                               to_templates=_to_seq_templates)
            full, head_f = p["encoder"], p["head"]
        elif dp * tp > 1:
            mesh = meshlib.get_mesh(
                dp * tp, axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS),
                shape=(dp, tp))
            if strategy == "moe":
                from .moe_encoder import (init_moe_encoder_params,
                                          make_moe_ep_dp_train_step)
                ne = self.get("numExperts")
                if ne < 1 or ne % tp:
                    raise ValueError(
                        f"numExperts {ne} must be >= 1 and divide over "
                        f"modelParallel {tp}")
                params = init_moe_encoder_params(
                    k_enc, self.get("numLayers"), self.get("dModel"), nh,
                    self.get("dFF"), ne)
                step, shard = make_moe_ep_dp_train_step(
                    mesh, nh, lr, nc, ne,
                    capacity_factor=self.get("capacityFactor"),
                    causal=self.get("causal"))
                gran = dp * tp           # tokens ride both mesh axes
                bs = min(max(self.get("batchSize"), gran), n)
                bs -= bs % gran
                if bs < gran:
                    raise ValueError(
                        f"{n} rows cannot fill a batch over {dp}x{tp} "
                        f"token shards")
            elif strategy == "pipeline":
                from .pipeline import make_pp_dp_train_step
                mb = self.get("numMicrobatches")
                if mb < 1:
                    raise ValueError(
                        f"numMicrobatches must be >= 1, got {mb}")
                if self.get("numLayers") % tp:
                    raise ValueError(
                        f"numLayers {self.get('numLayers')} must divide "
                        f"into {tp} pipeline stages")
                step, shard = make_pp_dp_train_step(
                    mesh, nh, lr, nc, num_microbatches=mb,
                    causal=self.get("causal"))
                gran = dp * mb
                bs = min(max(self.get("batchSize"), gran), n)
                bs -= bs % gran
                if bs < gran:
                    raise ValueError(
                        f"{n} rows cannot fill a batch of {dp} data shards "
                        f"x {mb} microbatches")
            else:
                if nh % tp:
                    raise ValueError(f"numHeads {nh} not divisible by "
                                     f"modelParallel {tp}")
                step, shard = make_tp_dp_train_step(
                    mesh, nh, lr, nc, self.get("causal"),
                    zero1=self.get("zero1"))
            p_sh, o_sh = shard(params, head)

            def _to_mesh_templates(p_st, o_st):
                # templates must carry the mesh layout (the step's
                # in_specs): shard() output is device-0-committed, so
                # re-place it on the right axes first. Params ride the
                # model axis; the optimizer state does too, EXCEPT under
                # ZeRO-1 where its flat chunks are additionally sharded
                # over the data axis ([tp, dp, chunk]).
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as _P
                spec_p = NamedSharding(mesh, _P(meshlib.MODEL_AXIS))
                spec_o = (NamedSharding(mesh, _P(meshlib.MODEL_AXIS,
                                                 meshlib.DATA_AXIS))
                          if self.get("zero1") else spec_p)
                return (jax.tree_util.tree_map(
                            lambda a: jax.device_put(a, spec_p), p_st),
                        jax.tree_util.tree_map(
                            lambda a: jax.device_put(a, spec_o), o_st))

            p_sh, o_sh = _train_loop(step, p_sh, o_sh, bs,
                                     to_templates=_to_mesh_templates)
            head_f = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[0], p_sh["head"])
            if strategy == "moe":
                from .moe_encoder import unshard_moe_encoder_params
                full = unshard_moe_encoder_params(
                    jax.tree_util.tree_map(np.asarray, p_sh)["encoder"],
                    self.get("numExperts"))
            elif strategy == "pipeline":
                # stage stack [pp, layers_per_stage, ...] -> flat layer list
                stage = jax.tree_util.tree_map(np.asarray, p_sh)["stage"]
                lps = self.get("numLayers") // tp
                full = {"layers": [
                    jax.tree_util.tree_map(lambda a, s=s, i=i: a[s][i], stage)
                    for s in range(tp) for i in range(lps)]}
            else:
                full = unshard_encoder_params(
                    jax.tree_util.tree_map(np.asarray, p_sh)["encoder"], nh)
        else:
            if strategy == "moe":
                raise ValueError(
                    "strategy='moe' trains expert-parallel — set "
                    "dataParallel/modelParallel so the mesh has > 1 device")
            step, init_opt = make_single_train_step(
                nh, lr, nc, self.get("causal"))
            p = {"encoder": params, "head": head}
            o = init_opt(p)
            p, o = _train_loop(step, p, o, bs)
            full, head_f = p["encoder"], p["head"]

        model = TransformerClassificationModel(
            weights=jax.tree_util.tree_map(np.asarray, full),
            head=jax.tree_util.tree_map(np.asarray, head_f))
        model.set("numHeads", nh)
        model.set("causal", self.get("causal"))
        model.set("inputCol", self.get("inputCol"))
        if strategy == "moe":
            model.set("numExperts", self.get("numExperts"))
            model.set("capacityFactor", self.get("capacityFactor"))
        return model


class TransformerClassificationModel(Model, _p.HasInputCol):
    """Mean-pool + linear head over the fitted encoder; emits prediction
    and probability columns (the DNNModel/ProbabilisticClassifier output
    convention)."""

    numHeads = _p.Param("numHeads", "attention heads", 4, int)
    causal = _p.Param("causal", "causal masking", False)
    numExperts = _p.Param("numExperts",
                          "Switch-MoE expert count (0 = dense FFN layers)",
                          0, int)
    capacityFactor = _p.Param("capacityFactor",
                              "MoE expert capacity factor", 2.0, float)
    weights = _p.Param("weights", "encoder parameter pytree", None,
                       complex=True)
    head = _p.Param("head", "classifier head {w, b}", None, complex=True)

    def __init__(self, weights=None, head=None, **kw):
        super().__init__()
        kw.setdefault("inputCol", "sequence")
        self._set(**kw)
        if weights is not None:
            self._set(weights=weights, head=head)

    def _compiled(self):
        """Acquire the jitted forward from the shared cached_jit registry
        — defining @jax.jit inside transform would retrace + recompile on
        every call, and the old per-instance `_fwd_cache` still recompiled
        identical configs per instance (round-11 churn fix; the MoE
        sharded forward shares the same registry)."""
        from ...compile.cache import cached_jit
        nh, causal = self.get("numHeads"), self.get("causal")
        ne, cf = self.get("numExperts"), self.get("capacityFactor")
        key = ("transformer_clf_fwd", nh, causal, ne, cf)

        if ne > 0:
            from .moe_encoder import moe_encoder_forward

            def fwd(p, h, xb):
                enc, _ = moe_encoder_forward(p, xb, nh, ne, cf,
                                             causal=causal)
                logits = enc.mean(axis=1) @ h["w"] + h["b"]
                return jax.nn.softmax(logits, axis=-1)
        else:
            def fwd(p, h, xb):
                enc = encoder_forward(p, xb, nh, causal,
                                      attention_impl="reference")
                logits = enc.mean(axis=1) @ h["w"] + h["b"]
                return jax.nn.softmax(logits, axis=-1)

        return cached_jit(fwd, key=key, name="transformer_clf_fwd")

    def transform(self, df: DataFrame) -> DataFrame:
        if self.get("weights") is None or self.get("head") is None:
            raise ValueError("TransformerClassificationModel needs fitted "
                             "`weights` and `head` parameter pytrees")
        x = _stack_sequences(df[self.get("inputCol")])
        proba = np.asarray(self._compiled()(self.get("weights"),
                                            self.get("head"),
                                            jnp.asarray(x)))
        out = df.with_column("probability", proba)
        return out.with_column("prediction",
                               proba.argmax(axis=1).astype(np.float64))


def make_sp_train_step(mesh, num_heads: int, learning_rate: float,
                       num_classes: int, causal: bool = False,
                       seq_axis: Optional[str] = None,
                       positional: bool = False,
                       attention_impl: str = "ring",
                       remat: bool = False):
    """Sequence-parallel transformer training over the mesh: the SEQUENCE
    axis is sharded (the long-context regime — activations for contexts far
    beyond one chip's HBM), parameters replicated, attention via the
    ppermute ring (ops/attention.ring_attention_sharded, default) or the
    all-to-all ulysses path (attention_impl="ulysses"); both reverse-mode
    transposes JAX derives exactly (ppermute transposes to the inverse
    rotation so gradients ride the ring backwards; all_to_all transposes
    to the opposite all_to_all).

    Gradient bookkeeping: encoder parameters act on LOCAL positions, so each
    shard holds a partial gradient — psum over the sequence axis. The head
    consumes the globally-pooled (replicated) encoding, so its gradients
    are already identical on every shard and must NOT be summed. The global
    mean-pool uses the psum-forward/identity-backward 'g' operator so the
    per-shard backward stays exact.

    Returns (step, init_opt): step(params, opt_state, x_sharded, y) with
    x [B, S, D] sharded on S over the axis; params/opt_state replicated.
    """
    import optax
    from ...parallel import mesh as meshlib
    from jax.sharding import PartitionSpec as P
    if attention_impl not in ("ring", "ulysses"):
        raise ValueError(f"attention_impl must be 'ring' or 'ulysses', "
                         f"got {attention_impl!r}")
    seq_axis = seq_axis or meshlib.DATA_AXIS
    n_sp = mesh.shape[seq_axis]
    tx = optax.adam(learning_rate)

    def loss_fn(params, x_local, y):
        enc = encoder_forward(params["encoder"], x_local, num_heads, causal,
                              axis_name=seq_axis, positional=positional,
                              attention_impl=attention_impl, remat=remat)
        s_glob = x_local.shape[1] * n_sp
        pooled = _reduce_from_model_shards(enc.sum(axis=1),
                                           seq_axis) / s_glob
        logits = pooled @ params["head"]["w"] + params["head"]["b"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.sum(jax.nn.one_hot(y, num_classes) * logp,
                                 axis=-1))

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        grads = {"encoder": jax.lax.psum(grads["encoder"], seq_axis),
                 "head": grads["head"]}
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    sharded = _shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(None, seq_axis, None), P()),
        out_specs=(P(), P(), P()), check_vma=False)

    return jax.jit(sharded), tx.init
