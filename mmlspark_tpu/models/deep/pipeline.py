"""Pipeline parallelism (pp) for the encoder stack — GPipe microbatching
over a mesh axis.

Completes the tp/pp/dp/sp/ep taxonomy (no reference analogue — SURVEY.md
§2.2/§5: the reference has no model parallelism at all).

Design: the layer stack is split into P contiguous stages, one per device
on the pipeline axis. The forward pass is ONE lax.scan over M + P - 1
ticks; each tick every stage applies its layer block to the activation it
received last tick and hands the result to the next stage via ppermute
(stage 0 reads microbatch t; the last stage collects microbatch t-(P-1)).
Bubble ticks compute on garbage and are masked at collection — the classic
GPipe bubble, P-1 wasted ticks out of M+P-1.

The backward pass is jax autodiff THROUGH the scan + ppermute: ppermute's
transpose is the reverse rotation, so the cotangents flow last-stage ->
first-stage in the mirrored schedule automatically — no hand-written
backward pipeline, and exactness vs the single-device stack is pinned by
tests (loss AND per-stage parameter gradients).

Composes with data parallelism on a 2-D (data, pipeline) mesh:
make_pp_dp_train_step shards the batch over DATA and the stages over
MODEL, reducing stage-parameter grads over data only.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...parallel.mesh import shard_map as _shard_map

from .transformer import encoder_layer

__all__ = ["stack_stage_params", "pipeline_forward", "make_pp_dp_train_step"]


def stack_stage_params(params, num_stages: int):
    """Split params["layers"] (list of per-layer dicts) into num_stages
    contiguous blocks and stack each block's layers along a leading axis:
    returns a pytree [num_stages, layers_per_stage, ...] whose axis 0 is
    sharded over the pipeline axis."""
    layers = params["layers"]
    if len(layers) % num_stages:
        raise ValueError(f"num_layers {len(layers)} must divide into "
                         f"{num_stages} pipeline stages")
    lps = len(layers) // num_stages
    stages = []
    for st in range(num_stages):
        block = layers[st * lps:(st + 1) * lps]
        stages.append(jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *block))
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stages)


def pipeline_forward(stage_params, x_mb, num_heads: int, axis_name: str,
                     causal: bool = False, remat: bool = False,
                     broadcast: bool = True,
                     attention_impl: str = "reference"):
    """Shard-local GPipe forward (call inside shard_map).

    stage_params: this stage's stacked layer block [layers_per_stage, ...].
    x_mb: [M, mb, S, D] microbatches (replicated across the pipeline axis).
    broadcast=True returns [M, mb, S, D] final-stack activations replicated
    on every stage (psum broadcast of the last stage's collection) — the
    INFERENCE convention. For training, use broadcast=False: the raw
    collection (zeros everywhere except the last stage), compute a LOCAL
    loss term from it, and reduce only AFTER value_and_grad —
    differentiating any in-graph reduction of the device-invariant loss
    (broadcast output or scalar psum alike) seeds every device's backward
    with its own copy's cotangent and grads come out x stages (caught by
    tests/test_pipeline_parallel.py::test_pipeline_gradients_match_dense).
    """
    p = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = x_mb.shape[0]
    perm = [(i, (i + 1) % p) for i in range(p)]

    def block(x):
        def body(h, lp):
            return encoder_layer(h, lp, num_heads, causal=causal,
                                 attention_impl=attention_impl), None
        h, _ = jax.lax.scan(body, x, stage_params)
        return h

    if remat:
        block = jax.checkpoint(block)

    def tick(carry, t):
        recv, coll = carry
        inp = jnp.where(idx == 0, x_mb[jnp.clip(t, 0, m - 1)], recv)
        out = block(inp)
        j = t - idx                       # microbatch index at this stage
        valid = (j >= 0) & (j < m) & (idx == p - 1)
        coll = jnp.where(
            valid,
            jax.lax.dynamic_update_index_in_dim(
                coll, out, jnp.clip(j, 0, m - 1), 0),
            coll)
        recv = jax.lax.ppermute(out, axis_name, perm)
        return (recv, coll), None

    recv0 = jnp.zeros_like(x_mb[0])
    coll0 = jnp.zeros_like(x_mb)
    (_, coll), _ = jax.lax.scan(tick, (recv0, coll0),
                                jnp.arange(m + p - 1))
    if not broadcast:
        return coll
    # broadcast the last stage's collected outputs to every stage
    return jax.lax.psum(jnp.where(idx == p - 1, coll, 0.0), axis_name)


def make_pp_dp_train_step(mesh, num_heads: int, learning_rate: float,
                          num_classes: int, num_microbatches: int,
                          causal: bool = False,
                          data_axis: Optional[str] = None,
                          model_axis: Optional[str] = None,
                          remat: bool = False):
    """One pipeline-parallel (x data-parallel) encoder training step.

    Returns (step, shard_params):
      params_s, opt_s = shard_params(full_params, head_params)
      params_s, opt_s, loss = step(params_s, opt_s, x, y)
    x: [B, S, D] (B divisible by data_shards * num_microbatches);
    y: [B] int labels. Stages ride the MODEL axis, batch rides DATA; the
    mean-pool + softmax head is replicated.

    The differentiated forward always uses reference attention — the fused
    flash kernel has no VJP (same reason the tp/sp TRAINING paths use
    reference, transformer.py); pipeline_forward exposes attention_impl
    for inference-only forwards.
    """
    import optax
    from ...parallel import mesh as meshlib
    from jax.sharding import PartitionSpec as P
    data_axis = data_axis or meshlib.DATA_AXIS
    model_axis = model_axis or meshlib.MODEL_AXIS
    pp = mesh.shape[model_axis]
    tx = optax.adam(learning_rate)
    m = num_microbatches

    def local_loss(params, x, y):
        b_loc = x.shape[0]
        x_mb = x.reshape(m, b_loc // m, *x.shape[1:])
        # training convention: raw collection (zeros off the last stage),
        # loss term on the last stage only, scalar psum — the broadcast
        # variant double-counts cotangents (see pipeline_forward docstring)
        coll = pipeline_forward(params["stage"], x_mb, num_heads,
                                model_axis, causal, remat=remat,
                                broadcast=False)
        enc = coll.reshape(b_loc, *x.shape[1:])
        pooled = enc.mean(axis=1)
        logits = pooled @ params["head"]["w"] + params["head"]["b"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        term = -jnp.mean(jnp.sum(jax.nn.one_hot(y, num_classes) * logp,
                                 axis=-1))
        idx = jax.lax.axis_index(model_axis)
        pp_count = jax.lax.psum(1, model_axis)
        # LOCAL masked term — no psum inside the differentiated function:
        # reducing a device-invariant loss in-graph seeds every device's
        # backward with its own copy's cotangent and grads come out
        # x stages (the house convention, make_tp_dp_train_step, reduces
        # AFTER value_and_grad; pinned by the pipeline gradient test)
        return jnp.where(idx == pp_count - 1, term, 0.0)

    def step(params, opt_state, x, y):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        opt_state = jax.tree_util.tree_map(lambda a: a[0], opt_state)
        loss, grads = jax.value_and_grad(local_loss)(params, x, y)
        loss = jax.lax.psum(loss, model_axis)   # value only, post-grad
        # stage params are disjoint across the pipeline: reduce over data
        # only. The replicated head contributes to the loss on the LAST
        # stage only, so its grads are zero elsewhere — the model-axis
        # psum restores the identical replicated update everywhere.
        grads = {"stage": grads["stage"],
                 "head": jax.tree_util.tree_map(
                     lambda g: jax.lax.psum(g, model_axis), grads["head"])}
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, data_axis), grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        lift = lambda a: a[None]
        # the model-axis psum above already made the loss model-invariant
        return (jax.tree_util.tree_map(lift, params),
                jax.tree_util.tree_map(lift, opt_state),
                jax.lax.pmean(loss, data_axis))

    sharded = _shard_map(
        step, mesh=mesh,
        in_specs=(P(model_axis), P(model_axis), P(data_axis), P(data_axis)),
        out_specs=(P(model_axis), P(model_axis), P()),
        check_vma=False)

    def shard_params(full_params, head):
        stacked_stages = stack_stage_params(full_params, pp)
        shards = [{"stage": jax.tree_util.tree_map(lambda a, s=st: a[s],
                                                   stacked_stages),
                   "head": head} for st in range(pp)]
        stack = lambda *xs: jnp.stack(xs)
        stacked = jax.tree_util.tree_map(stack, *shards)
        opt_shards = [tx.init(s) for s in shards]
        return stacked, jax.tree_util.tree_map(stack, *opt_shards)

    return jax.jit(sharded), shard_params
