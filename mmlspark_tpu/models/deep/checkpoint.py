"""Sharded checkpoint/resume for the distributed trainers.

The reference's checkpoint story is model-string / model-bytes persistence
of FITTED models (LightGBMBooster.scala:277-296, VowpalWabbitBaseModel
`initialModel`, core/serialize/ComplexParam.scala) — its deep path is
inference-only, so it never needs optimizer state. The TPU build trains
(tensor/pipeline/expert/sequence parallel), so mid-training state is a
first-class artifact: params AND optimizer state, laid out exactly as the
shard_map'd step consumes them (leading model-shard axis; ZeRO-1's
dp-chunked flat optimizer state).

Orbax writes each jax.Array with its sharding: every host saves only the
shards it owns (OCDBT), and restore re-places shards onto the SAME mesh
layout the templates carry — so a save from an N-host run restores onto an
N-host run without gathering anything through one host. Resume equivalence
(save -> restore -> identical loss trace) is pinned by
tests/test_deep_checkpoint.py on the virtual 8-device mesh.

Elastic additions (ISSUE 10): every save records a sibling mesh manifest
(`<step_dir>.mesh.json`, written through the resilience atomic-write
helper) naming the mesh axes/extents the state was laid out on.
`restore_train_state` is the SAME-MESH contract — a mismatched mesh now
fails with an error naming both shapes instead of orbax's raw sharding
error — while `restore_train_state_resharded` is the documented elastic
route for resuming across device counts/layouts: the arrays are read back
from the (sharding-agnostic) on-disk tree and re-placed onto whatever mesh
the templates carry. `keep_last` bounds the step-dir history (crash
recovery needs the last snapshot or two, not every epoch of a long run).
"""

from __future__ import annotations

import json
import os
import shutil
import warnings
from typing import Any, Optional, Tuple

import jax

from ...parallel.mesh import describe_mesh
from ...resilience.elastic import atomic_write_text, publish_event

__all__ = ["save_train_state", "restore_train_state",
           "restore_train_state_resharded", "latest_step", "gc_step_dirs"]


_CKPTR = None


def _checkpointer():
    # one process-wide checkpointer: StandardCheckpointer is an
    # AsyncCheckpointer whose worker threads are never GC'd, so a
    # per-call instance would leak a thread pool per checkpoint
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _step_dir(path: str, step: Optional[int]) -> str:
    return os.path.join(path, f"step_{step:08d}") if step is not None else path


def _mesh_manifest_path(step_dir: str) -> str:
    # SIBLING of the orbax dir, not inside it: orbax owns the step dir's
    # contents and a foreign file must not trip its format validation
    return step_dir.rstrip(os.sep) + ".mesh.json"


def _tree_mesh(*trees: Any) -> Optional[dict]:
    """Mesh descriptor of the first NamedSharding-bearing leaf (the
    training state is laid out on ONE mesh; mixed-mesh trees don't occur
    in this codebase)."""
    for leaf in jax.tree_util.tree_leaves(trees):
        sh = getattr(leaf, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "axis_names", None):
            try:
                return describe_mesh(mesh)
            except Exception:  # noqa: BLE001 - descriptor is best-effort
                return None
    return None


def save_train_state(path: str, params: Any, opt_state: Any,
                     step: Optional[int] = None,
                     keep_last: Optional[int] = None) -> str:
    """Write (params, opt_state) under `path` (optionally path/step_NNNNNNNN).

    Arrays keep their shardings; each process writes only local shards. A
    sibling ``<dir>.mesh.json`` manifest records the mesh layout (used by
    restore to distinguish same-mesh from needs-reshard). ``keep_last``
    applies keep-last-K retention to the step-dir history (None keeps
    everything — the pre-elastic behavior). Returns the directory
    written."""
    d = _step_dir(os.path.abspath(path), step)
    ckptr = _checkpointer()
    ckptr.save(d, {"params": params, "opt_state": opt_state}, force=True)
    ckptr.wait_until_finished()
    desc = _tree_mesh(params, opt_state)
    if desc is not None:
        atomic_write_text(_mesh_manifest_path(d),
                          json.dumps({"schema_version": 1, "mesh": desc,
                                      "step": step}, sort_keys=True))
    if keep_last is not None and step is not None:
        gc_step_dirs(os.path.abspath(path), keep_last)
    return d


def latest_step(path: str) -> Optional[int]:
    """Largest step_NNNNNNNN under path, or None."""
    try:
        # fully-numeric suffix only: an interrupted save leaves a sibling
        # 'step_N.orbax-checkpoint-tmp-<ts>' dir which must not crash (or
        # win) the scan — crash recovery is exactly when this runs; the
        # .mesh.json manifests are filtered by the same rule
        steps = [int(n.split("_", 1)[1]) for n in os.listdir(path)
                 if n.startswith("step_") and n.split("_", 1)[1].isdigit()]
    except FileNotFoundError:
        return None
    return max(steps) if steps else None


def gc_step_dirs(path: str, keep_last: int) -> int:
    """Keep-last-K retention for orbax step dirs: remove the oldest
    step_NNNNNNNN dirs (and their mesh manifests) beyond ``keep_last``.
    Interrupted-save tmp dirs are untouched (orbax's own cleanup owns
    them). Returns the number of step dirs removed."""
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    try:
        steps = sorted(int(n.split("_", 1)[1]) for n in os.listdir(path)
                       if n.startswith("step_")
                       and n.split("_", 1)[1].isdigit())
    except FileNotFoundError:
        return 0
    removed = 0
    for s in steps[:-keep_last]:
        d = _step_dir(path, s)
        shutil.rmtree(d, ignore_errors=True)
        try:
            os.remove(_mesh_manifest_path(d))
        except OSError:
            pass
        removed += 1
    if removed:
        publish_event("gc", outcome="step_dirs")
    return removed


def _read_mesh_manifest(step_dir: str) -> Optional[dict]:
    try:
        with open(_mesh_manifest_path(step_dir), encoding="utf-8") as fh:
            return json.load(fh).get("mesh")
    except (OSError, ValueError):
        return None


def _abstract(params_like: Any, opt_state_like: Any) -> dict:
    def absify(a):
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)

    return {"params": jax.tree_util.tree_map(absify, params_like),
            "opt_state": jax.tree_util.tree_map(absify, opt_state_like)}


def restore_train_state(path: str, params_like: Any, opt_state_like: Any,
                        step: Optional[int] = None) -> Tuple[Any, Any]:
    """SAME-MESH restore: (params, opt_state) with the templates' shapes,
    dtypes AND shardings, so the restored arrays drop straight into the
    compiled step function without relayout.

    Templates must carry the TARGET shardings: a live training state (step
    output) or a previously restored state. A fresh `shard_params` output
    does NOT work — its arrays sit committed on one device, and restoring
    with that layout hands shard_map single-device operands it rejects.

    The checkpoint's mesh manifest is checked against the templates'
    mesh: a mismatch (resuming after losing a chip, or onto a resized
    slice) raises a ValueError NAMING BOTH SHAPES — use
    `restore_train_state_resharded` for that, which re-places the saved
    arrays onto the current mesh."""
    d = _step_dir(os.path.abspath(path), step)
    saved = _read_mesh_manifest(d)
    cur = _tree_mesh(params_like, opt_state_like)
    if saved is not None and cur is not None and saved != cur:
        raise ValueError(
            f"checkpoint {d} was written on mesh "
            f"{dict(zip(saved['axis_names'], saved['shape']))} but the "
            f"restore templates are laid out on mesh "
            f"{dict(zip(cur['axis_names'], cur['shape']))}: a same-mesh "
            f"restore cannot cross mesh shapes. Use "
            f"restore_train_state_resharded(...) to restore this state "
            f"onto the current mesh (re-shard-on-restore), or rebuild the "
            f"saved mesh")
    restored = _checkpointer().restore(d, _abstract(params_like,
                                                    opt_state_like))
    return restored["params"], restored["opt_state"]


def restore_train_state_resharded(path: str, params_like: Any,
                                  opt_state_like: Any,
                                  step: Optional[int] = None
                                  ) -> Tuple[Any, Any]:
    """ELASTIC restore across mesh layouts: resume a state saved at one
    device count/topology onto whatever mesh the templates carry.

    The on-disk tree (OCDBT) is sharding-agnostic: each array is read
    back from the hosts' shard files and re-placed directly onto the
    templates' shardings — the re-shard-on-restore route (restore to the
    host-visible tree, place onto the current mesh) that replaces the
    same-mesh contract when the pool shrinks or grows between runs. The
    saved mesh manifest is informational here (a mismatch is the expected
    case); numerically the restored arrays are identical to a same-mesh
    restore, so a resumed step matches to fp determinism."""
    d = _step_dir(os.path.abspath(path), step)
    saved = _read_mesh_manifest(d)
    cur = _tree_mesh(params_like, opt_state_like)
    if saved is not None and cur is not None and saved == cur:
        warnings.warn(
            f"restore_train_state_resharded({d}): saved and current mesh "
            f"match ({dict(zip(cur['axis_names'], cur['shape']))}) — the "
            f"same-mesh restore_train_state is the cheaper path",
            stacklevel=2)
    restored = _checkpointer().restore(d, _abstract(params_like,
                                                    opt_state_like))
    publish_event("resume", outcome="reshard")
    return restored["params"], restored["opt_state"]
