"""Sharded checkpoint/resume for the distributed trainers.

The reference's checkpoint story is model-string / model-bytes persistence
of FITTED models (LightGBMBooster.scala:277-296, VowpalWabbitBaseModel
`initialModel`, core/serialize/ComplexParam.scala) — its deep path is
inference-only, so it never needs optimizer state. The TPU build trains
(tensor/pipeline/expert/sequence parallel), so mid-training state is a
first-class artifact: params AND optimizer state, laid out exactly as the
shard_map'd step consumes them (leading model-shard axis; ZeRO-1's
dp-chunked flat optimizer state).

Orbax writes each jax.Array with its sharding: every host saves only the
shards it owns (OCDBT), and restore re-places shards onto the SAME mesh
layout the templates carry — so a save from an N-host run restores onto an
N-host run without gathering anything through one host. Resume equivalence
(save -> restore -> identical loss trace) is pinned by
tests/test_deep_checkpoint.py on the virtual 8-device mesh.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax

__all__ = ["save_train_state", "restore_train_state", "latest_step"]


_CKPTR = None


def _checkpointer():
    # one process-wide checkpointer: StandardCheckpointer is an
    # AsyncCheckpointer whose worker threads are never GC'd, so a
    # per-call instance would leak a thread pool per checkpoint
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _step_dir(path: str, step: Optional[int]) -> str:
    return os.path.join(path, f"step_{step:08d}") if step is not None else path


def save_train_state(path: str, params: Any, opt_state: Any,
                     step: Optional[int] = None) -> str:
    """Write (params, opt_state) under `path` (optionally path/step_NNNNNNNN).

    Arrays keep their shardings; each process writes only local shards.
    Returns the directory written."""
    d = _step_dir(os.path.abspath(path), step)
    ckptr = _checkpointer()
    ckptr.save(d, {"params": params, "opt_state": opt_state}, force=True)
    ckptr.wait_until_finished()
    return d


def latest_step(path: str) -> Optional[int]:
    """Largest step_NNNNNNNN under path, or None."""
    try:
        # fully-numeric suffix only: an interrupted save leaves a sibling
        # 'step_N.orbax-checkpoint-tmp-<ts>' dir which must not crash (or
        # win) the scan — crash recovery is exactly when this runs
        steps = [int(n.split("_", 1)[1]) for n in os.listdir(path)
                 if n.startswith("step_") and n.split("_", 1)[1].isdigit()]
    except FileNotFoundError:
        return None
    return max(steps) if steps else None


def restore_train_state(path: str, params_like: Any, opt_state_like: Any,
                        step: Optional[int] = None) -> Tuple[Any, Any]:
    """Restore (params, opt_state) with the templates' shapes, dtypes AND
    shardings, so the restored arrays drop straight into the compiled step
    function without relayout.

    Templates must carry the TARGET shardings: a live training state (step
    output) or a previously restored state. A fresh `shard_params` output
    does NOT work — its arrays sit committed on one device, and restoring
    with that layout hands shard_map single-device operands it rejects."""
    d = _step_dir(os.path.abspath(path), step)

    def absify(a):
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)

    abstract = {"params": jax.tree_util.tree_map(absify, params_like),
                "opt_state": jax.tree_util.tree_map(absify, opt_state_like)}
    restored = _checkpointer().restore(d, abstract)
    return restored["params"], restored["opt_state"]
