"""DNNModel — batched jit DNN inference over DataFrame columns.

Reference: cntk/CNTKModel.scala:145-532 — broadcast serialized graph, feed/
fetch dicts mapping CNTK variables to columns (:204-223), minibatch ->
`applyCNTKFunction` -> flatten (:490-530), per-partition JNI eval hot loop
(:30-140). Here the graph is a flax module jitted once; minibatching
(FixedMiniBatchTransformer -> FlattenBatch in the reference) collapses into
padded fixed-size device batches inside transform, and the "broadcast" is XLA
constant/device placement.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame
from ...core.pipeline import Model


class GraphModel:
    """A loaded network: flax module + variables + zoo schema
    (the SerializableFunction equivalent — com/microsoft/CNTK/
    SerializableFunction.scala:17-120)."""

    def __init__(self, module, variables, schema):
        self.module = module
        self.variables = variables
        self.schema = schema
        self._jitted = {}

    def apply_fn(self, layer: Optional[str]):
        """jitted apply capturing the fetch layer (CNTK outputMap analogue)."""
        key = layer
        if key not in self._jitted:
            def fn(variables, x):
                return self.module.apply(variables, x, capture=layer)
            self._jitted[key] = jax.jit(fn)
        return self._jitted[key]

    def __reduce__(self):
        # pickled via the zoo name + host numpy leaves (model-bytes broadcast
        # analogue, CNTKModel.scala:411-413)
        leaves, treedef = jax.tree.flatten(self.variables)
        return (_rebuild_graph_model,
                (self.schema.name, [np.asarray(l) for l in leaves]))


def _rebuild_graph_model(name: str, leaves):
    from .resnet import _ZOO
    schema = _ZOO[name]()
    h, w, c = schema.input_dims
    # eval_shape gets the variable treedef without materializing weights
    shapes = jax.eval_shape(schema.module.init, jax.random.PRNGKey(0),
                            jnp.zeros((1, h, w, c), jnp.float32))
    _, treedef = jax.tree.flatten(shapes)
    return GraphModel(module=schema.module,
                      variables=jax.tree.unflatten(treedef, leaves),
                      schema=schema)


class DNNModel(Model, _p.HasInputCol, _p.HasOutputCol, _p.HasBatchSize):
    """Reference surface: CNTKModel (cntk/CNTKModel.scala:145).

    inputCol accepts a stacked [N,H,W,C] float column, an object column of
    HWC images, or flat CHW vectors (UnrollImage output — reshaped back using
    the model schema's input dims)."""

    model = _p.Param("model", "GraphModel to evaluate", None, complex=True)
    outputNode = _p.Param("outputNode", "layer to fetch (None = final "
                          "logits); the CNTK outputMap analogue", None)
    normalize = _p.Param("normalize", "apply schema mean/std normalization",
                         True, bool)
    scaleFactor = _p.Param(
        "scaleFactor", "divide pixel values by this before normalization; "
        "0 = by dtype (integer images / 255, float images / 1 — "
        "deterministic, never inferred from batch contents)", 0.0, float)

    def __init__(self, model: Optional[GraphModel] = None, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "output")
        kw.setdefault("batchSize", 16)
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)

    set_model = lambda self, m: self.set("model", m)  # CNTKModel.setModel

    def _coerce_batch(self, col: np.ndarray) -> np.ndarray:
        gm: GraphModel = self.get("model")
        h, w, c = gm.schema.input_dims
        from .image import resize_image
        if col.dtype == object:
            int_input = all(np.asarray(v).dtype.kind in "iu" for v in col)
            imgs = []
            for v in col:  # per-image resize handles heterogeneous sizes
                a = np.asarray(v, np.float32)
                if a.ndim == 2:
                    a = a[:, :, None]
                if a.shape[:2] != (h, w):
                    a = resize_image(a, h, w)
                imgs.append(a)
            arr = np.stack(imgs)
        else:
            int_input = col.dtype.kind in "iu"
            arr = np.asarray(col, np.float32)
        if arr.ndim == 2:  # flat CHW vectors (UnrollImage convention)
            arr = arr.reshape(len(arr), c, h, w).transpose(0, 2, 3, 1)
        if arr.ndim == 3:
            arr = arr[..., None]
        if arr.shape[1:3] != (h, w):
            resized = [resize_image(a, h, w) for a in arr]
            arr = np.stack(resized)
        if self.get("normalize"):
            scale = self.get("scaleFactor") or (255.0 if int_input else 1.0)
            arr = (arr / scale - gm.schema.mean) / gm.schema.std
        return arr

    def transform(self, df: DataFrame) -> DataFrame:
        gm: GraphModel = self.get("model")
        arr = self._coerce_batch(df[self.get("inputCol")])
        n = len(arr)
        b = self.get("batchSize")
        fn = gm.apply_fn(self.get("outputNode"))
        outs = []
        for start in range(0, n, b):
            chunk = arr[start:start + b]
            pad = b - len(chunk)
            if pad:  # fixed batch shape => one compiled program
                chunk = np.concatenate(
                    [chunk, np.zeros((pad,) + chunk.shape[1:], np.float32)])
            res = np.asarray(fn(gm.variables, jnp.asarray(chunk)))
            outs.append(res[:b - pad] if pad else res)
        out = np.concatenate(outs, axis=0)
        return df.with_column(self.get("outputCol"),
                              out.reshape(n, -1).astype(np.float64))


class ImageFeaturizer(Model, _p.HasInputCol, _p.HasOutputCol):
    """Resize -> normalize -> headless DNN forward (image/ImageFeaturizer.
    scala:40-191; `cutOutputLayers=1` drops the classifier head and emits
    pooled features)."""

    cutOutputLayers = _p.Param("cutOutputLayers", "how many output layers to "
                               "cut (1 = pooled features, 0 = logits)", 1, int)
    dnnModel = _p.Param("dnnModel", "wrapped GraphModel", None, complex=True)
    batchSize = _p.Param("batchSize", "inference minibatch", 16, int)

    def __init__(self, model: Optional[GraphModel] = None, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "features")
        super().__init__(**kw)
        if model is not None:
            self.set("dnnModel", model)

    def set_model(self, model_or_name) -> "ImageFeaturizer":
        """Accepts a GraphModel or a zoo name (setModel(ModelSchema) parity)."""
        if isinstance(model_or_name, str):
            from .resnet import ModelDownloader
            model_or_name = ModelDownloader().download_by_name(model_or_name)
        return self.set("dnnModel", model_or_name)

    setModel = set_model

    def transform(self, df: DataFrame) -> DataFrame:
        gm: GraphModel = self.get("dnnModel")
        layer = "pool" if self.get("cutOutputLayers") >= 1 else None
        dnn = DNNModel(model=gm, inputCol=self.get("inputCol"),
                       outputCol=self.get("outputCol"),
                       outputNode=layer, batchSize=self.get("batchSize"))
        return dnn.transform(df)
