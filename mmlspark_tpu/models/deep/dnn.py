"""DNNModel — batched jit DNN inference over DataFrame columns.

Reference: cntk/CNTKModel.scala:145-532 — broadcast serialized graph, feed/
fetch dicts mapping CNTK variables to columns (:204-223), minibatch ->
`applyCNTKFunction` -> flatten (:490-530), per-partition JNI eval hot loop
(:30-140). Here the graph is a flax module jitted once; minibatching
(FixedMiniBatchTransformer -> FlattenBatch in the reference) collapses into
padded fixed-size device batches inside transform, and the "broadcast" is XLA
constant/device placement.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame
from ...core.pipeline import Model


class GraphModel:
    """A loaded network: flax module + variables + zoo schema
    (the SerializableFunction equivalent — com/microsoft/CNTK/
    SerializableFunction.scala:17-120)."""

    def __init__(self, module, variables, schema):
        self.module = module
        self.variables = variables
        self.schema = schema
        # AOT serving artifacts (compile/aot.py), armed by
        # load_serving_artifacts; keyed per (layer, batch) bucket.
        # (__reduce__ rebuilds via __init__, so a pickled GraphModel
        # rehydrates with these reset — executables are process-local.)
        self._aot_store = None
        self._aot_cache: dict = {}

    def apply_fn(self, layer: Optional[str]):
        """jitted apply capturing the fetch layer (CNTK outputMap analogue).

        Acquired via the shared cached_jit registry instead of a
        per-instance dict: two GraphModels of the same zoo schema (the
        common featurizer fleet shape) share ONE executable per fetch
        layer instead of recompiling per instance. The flax module repr
        (its full static config) disambiguates hand-built models that
        reuse a zoo name."""
        from ...compile.cache import cached_jit
        module = self.module

        def fn(variables, x):
            return module.apply(variables, x, capture=layer)

        return cached_jit(
            fn, key=("dnn_apply", self.schema.name, repr(module), layer),
            name="dnn_apply")

    # --------------------------------------------------------- AOT export
    def _aot_name(self, layer, batch: int) -> str:
        return f"apply_{layer or 'logits'}_b{batch}"

    def export_serving_artifacts(self, directory: str, batch_sizes=(1, 16),
                                 layers=(None, "pool"),
                                 include_compiled: bool = True) -> list:
        """AOT-export the forward for the given fetch layers and batch
        buckets into ``directory`` beside the zoo checkpoint: the portable
        ``jax.export`` layer plus (by default) the pre-compiled executable
        for this exact backend. A serving/featurizer worker loading these
        starts without tracing or compiling the CNN — the reference ships
        pre-built model artifacts to executors the same way
        (ModelDownloader/CNTKModel)."""
        from jax import export as jax_export

        from ...compile.aot import AOTStore, compile_for_export
        store = AOTStore(directory)
        h, w, c = self.schema.input_dims
        vspecs = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l),
                                           jnp.asarray(l).dtype),
            self.variables)
        names = []
        for layer in layers:
            fn = self.apply_fn(layer).jitted
            for b in batch_sizes:
                xspec = jax.ShapeDtypeStruct((int(b), h, w, c), jnp.float32)
                exported = jax_export.export(fn)(vspecs, xspec)
                compiled = (compile_for_export(fn, vspecs, xspec)
                            if include_compiled else None)
                name = self._aot_name(layer, int(b))
                store.save(name, exported, compiled=compiled, extra={
                    "entry_point": "dnn_apply", "model": self.schema.name,
                    "layer": layer or "logits", "batch": int(b)})
                names.append(name)
        return names

    def load_serving_artifacts(self, directory: str) -> "GraphModel":
        """Arm AOT serving: apply_fn consults ``directory``'s manifest per
        (layer, batch bucket) with counted fallback to fresh JIT."""
        from ...compile.aot import AOTStore
        self._aot_store = AOTStore(directory)
        self._aot_cache = {}
        return self

    def _aot_apply(self, layer, variables, x):
        """Exported-executable forward for this (layer, batch), or None
        (counted fallback) so the caller JITs. Never raises."""
        if self._aot_store is None:
            return None
        from ...compile.aot import count_fallback, load_serving_callable
        name = self._aot_name(layer, int(x.shape[0]))
        if name not in self._aot_cache:
            self._aot_cache[name] = load_serving_callable(
                self._aot_store, name, (variables, x),
                expect_nr_devices=1)
        fn = self._aot_cache[name]
        if fn is None:
            return None
        try:
            return fn(variables, x)
        except Exception:
            count_fallback("call_error", name)
            self._aot_cache[name] = None
            return None

    def __reduce__(self):
        # pickled via the zoo name + host numpy leaves (model-bytes broadcast
        # analogue, CNTKModel.scala:411-413)
        leaves, treedef = jax.tree.flatten(self.variables)
        return (_rebuild_graph_model,
                (self.schema.name, [np.asarray(l) for l in leaves]))


def _rebuild_graph_model(name: str, leaves):
    from .resnet import _ZOO
    schema = _ZOO[name]()
    h, w, c = schema.input_dims
    # eval_shape gets the variable treedef without materializing weights
    shapes = jax.eval_shape(schema.module.init, jax.random.PRNGKey(0),
                            jnp.zeros((1, h, w, c), jnp.float32))
    _, treedef = jax.tree.flatten(shapes)
    return GraphModel(module=schema.module,
                      variables=jax.tree.unflatten(treedef, leaves),
                      schema=schema)


class DNNModel(Model, _p.HasInputCol, _p.HasOutputCol, _p.HasBatchSize):
    """Reference surface: CNTKModel (cntk/CNTKModel.scala:145).

    inputCol accepts a stacked [N,H,W,C] float column, an object column of
    HWC images, or flat CHW vectors (UnrollImage output — reshaped back using
    the model schema's input dims)."""

    model = _p.Param("model", "GraphModel to evaluate", None, complex=True)
    outputNode = _p.Param("outputNode", "layer to fetch (None = final "
                          "logits); the CNTK outputMap analogue", None)
    normalize = _p.Param("normalize", "apply schema mean/std normalization",
                         True, bool)
    scaleFactor = _p.Param(
        "scaleFactor", "divide pixel values by this before normalization; "
        "0 = by dtype (integer images / 255, float images / 1 — "
        "deterministic, never inferred from batch contents)", 0.0, float)

    def __init__(self, model: Optional[GraphModel] = None, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "output")
        kw.setdefault("batchSize", 16)
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)

    set_model = lambda self, m: self.set("model", m)  # CNTKModel.setModel

    def _coerce_batch(self, col: np.ndarray) -> np.ndarray:
        gm: GraphModel = self.get("model")
        h, w, c = gm.schema.input_dims
        from .image import resize_image
        if col.dtype == object:
            int_input = all(np.asarray(v).dtype.kind in "iu" for v in col)
            imgs = []
            for v in col:  # per-image resize handles heterogeneous sizes
                a = np.asarray(v, np.float32)
                if a.ndim == 2:
                    a = a[:, :, None]
                if a.shape[:2] != (h, w):
                    a = resize_image(a, h, w)
                imgs.append(a)
            arr = np.stack(imgs)
        else:
            int_input = col.dtype.kind in "iu"
            arr = np.asarray(col, np.float32)
        if arr.ndim == 2:  # flat CHW vectors (UnrollImage convention)
            arr = arr.reshape(len(arr), c, h, w).transpose(0, 2, 3, 1)
        if arr.ndim == 3:
            arr = arr[..., None]
        if arr.shape[1:3] != (h, w):
            resized = [resize_image(a, h, w) for a in arr]
            arr = np.stack(resized)
        if self.get("normalize"):
            scale = self.get("scaleFactor") or (255.0 if int_input else 1.0)
            arr = (arr / scale - gm.schema.mean) / gm.schema.std
        return arr

    def transform(self, df: DataFrame) -> DataFrame:
        gm: GraphModel = self.get("model")
        arr = self._coerce_batch(df[self.get("inputCol")])
        n = len(arr)
        b = self.get("batchSize")
        layer = self.get("outputNode")
        fn = None  # fresh-JIT path acquired lazily (AOT may cover all)
        outs = []
        for start in range(0, n, b):
            chunk = arr[start:start + b]
            pad = b - len(chunk)
            if pad:  # fixed batch shape => one compiled program
                chunk = np.concatenate(
                    [chunk, np.zeros((pad,) + chunk.shape[1:], np.float32)])
            xb = jnp.asarray(chunk)
            res = gm._aot_apply(layer, gm.variables, xb)
            if res is None:
                if fn is None:
                    fn = gm.apply_fn(layer)
                res = fn(gm.variables, xb)
            res = np.asarray(res)
            outs.append(res[:b - pad] if pad else res)
        out = np.concatenate(outs, axis=0)
        return df.with_column(self.get("outputCol"),
                              out.reshape(n, -1).astype(np.float64))


class ImageFeaturizer(Model, _p.HasInputCol, _p.HasOutputCol):
    """Resize -> normalize -> headless DNN forward (image/ImageFeaturizer.
    scala:40-191; `cutOutputLayers=1` drops the classifier head and emits
    pooled features)."""

    cutOutputLayers = _p.Param("cutOutputLayers", "how many output layers to "
                               "cut (1 = pooled features, 0 = logits)", 1, int)
    dnnModel = _p.Param("dnnModel", "wrapped GraphModel", None, complex=True)
    batchSize = _p.Param("batchSize", "inference minibatch", 16, int)

    def __init__(self, model: Optional[GraphModel] = None, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "features")
        super().__init__(**kw)
        if model is not None:
            self.set("dnnModel", model)

    def set_model(self, model_or_name) -> "ImageFeaturizer":
        """Accepts a GraphModel or a zoo name (setModel(ModelSchema) parity)."""
        if isinstance(model_or_name, str):
            from .resnet import ModelDownloader
            model_or_name = ModelDownloader().download_by_name(model_or_name)
        return self.set("dnnModel", model_or_name)

    setModel = set_model

    def transform(self, df: DataFrame) -> DataFrame:
        gm: GraphModel = self.get("dnnModel")
        layer = "pool" if self.get("cutOutputLayers") >= 1 else None
        dnn = DNNModel(model=gm, inputCol=self.get("inputCol"),
                       outputCol=self.get("outputCol"),
                       outputNode=layer, batchSize=self.get("batchSize"))
        return dnn.transform(df)
