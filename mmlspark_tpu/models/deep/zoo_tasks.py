"""Offline zoo-training task constructions.

The reference's model zoo trains on external datasets its build downloads
(downloader/ModelDownloader.scala:27-250); this environment has zero
egress, so the bundled checkpoints are trained on DETERMINISTIC tasks
composed from the only real image data available offline (sklearn digits).
The constructions live here — in the package, not the training scripts —
so the CI gates that re-derive the held-out split import the SAME code the
checkpoint was trained with (split drift between script and test would
silently invalidate the accuracy claim).
"""

from __future__ import annotations

import numpy as np

CLUTTER_SEED = 23
CLUTTER_HW = 32
CLUTTER_VARIANTS = 2  # clutter variants per base image


def make_clutter_dataset(seed: int = CLUTTER_SEED):
    """DigitsClutter-32: 32x32 canvas; the 16x16-upscaled sklearn digit at a
    RANDOM OFFSET; two quarter-size distractor fragments cropped from OTHER
    digit images at reduced intensity; Gaussian pixel noise. 10-class but —
    unlike centered digits — demands translation invariance and clutter
    rejection.

    Split hygiene: each base image contributes CLUTTER_VARIANTS variants and
    both land on the SAME side of the 80/20 split (split by base image, then
    augment) so no pixel content leaks train->test.

    Returns (xtr, ytr, xte, yte): [N, 32, 32, 3] float32 in [0, 1] / int32.
    """
    from sklearn.datasets import load_digits
    h = w = CLUTTER_HW
    d = load_digits()
    imgs8 = d.images.astype(np.float32) / 16.0          # [N, 8, 8]
    labels = d.target.astype(np.int32)
    n = len(labels)
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    n_tr = int(0.8 * n)
    splits = {"train": order[:n_tr], "test": order[n_tr:]}

    out = {}
    for part, idx in splits.items():
        xs, ys = [], []
        for i in idx:
            big = np.repeat(np.repeat(imgs8[i], 2, 0), 2, 1)  # 16x16
            for _ in range(CLUTTER_VARIANTS):
                canvas = np.zeros((h, w), np.float32)
                # distractors first so the true digit overwrites them;
                # drawn from THIS part's base images only — a cross-part
                # draw would paste test pixels into training canvases,
                # breaking the no-leakage guarantee above
                for _d in range(2):
                    j = int(idx[rng.integers(0, len(idx))])
                    frag = imgs8[j]                            # 8x8
                    fy = int(rng.integers(0, h - 8))
                    fx = int(rng.integers(0, w - 8))
                    canvas[fy:fy + 8, fx:fx + 8] = np.maximum(
                        canvas[fy:fy + 8, fx:fx + 8], 0.6 * frag)
                oy = int(rng.integers(0, h - 16))
                ox = int(rng.integers(0, w - 16))
                region = canvas[oy:oy + 16, ox:ox + 16]
                canvas[oy:oy + 16, ox:ox + 16] = np.where(
                    big > 0.05, big, region)
                canvas = np.clip(
                    canvas + rng.normal(0, 0.05, (h, w)).astype(np.float32),
                    0.0, 1.0)
                xs.append(canvas)
                ys.append(labels[i])
        x = np.stack(xs)[..., None].repeat(3, axis=-1)       # [M, H, W, 3]
        out[part] = (x.astype(np.float32), np.asarray(ys, np.int32))
    return out["train"] + out["test"]
