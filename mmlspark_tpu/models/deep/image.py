"""Image transformer stages — decode/resize/crop/color/flip/blur/threshold.

Reference: opencv/ImageTransformer.scala:26-220,280-380 (OpenCV `Mat` stage
pipeline: ResizeImage, CropImage, ColorFormat, Flip, Blur, Threshold,
GaussianKernel applied per row via UDF), image/ResizeImageTransformer.scala
(AWT resize), image/UnrollImage.scala:24-201 (HWC struct -> flat CHW vector),
image/ImageSetAugmenter.scala:15-80 (flip-LR/UD augmentation).

TPU design: images batch into a dense [N,H,W,C] tensor whenever shapes agree
and every stage is a vectorized numpy/jax op over the whole batch — no per-row
UDF, no native Mat objects.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...core import params as _p
from ...core.dataframe import DataFrame
from ...core.pipeline import Transformer


def _as_image(v) -> np.ndarray:
    a = np.asarray(v, np.float32)
    if a.ndim == 2:
        a = a[:, :, None]
    return a


def resize_image(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear resize via jax.image (XLA kernel; batch-friendly)."""
    import jax
    import jax.numpy as jnp
    return np.asarray(jax.image.resize(
        jnp.asarray(img), (height, width, img.shape[2]), "bilinear"))


def _box_blur(img: np.ndarray, kh: int, kw: int) -> np.ndarray:
    """Separable box blur with edge padding (cv2.blur semantics)."""
    out = img.astype(np.float64)
    if kh > 1:
        pad = np.pad(out, ((kh // 2, kh - 1 - kh // 2), (0, 0), (0, 0)),
                     mode="edge")
        csum = np.cumsum(pad, axis=0)
        csum = np.concatenate([np.zeros_like(csum[:1]), csum], axis=0)
        out = (csum[kh:] - csum[:-kh]) / kh
    if kw > 1:
        pad = np.pad(out, ((0, 0), (kw // 2, kw - 1 - kw // 2), (0, 0)),
                     mode="edge")
        csum = np.cumsum(pad, axis=1)
        csum = np.concatenate([np.zeros_like(csum[:, :1]), csum], axis=1)
        out = (csum[:, kw:] - csum[:, :-kw]) / kw
    return out.astype(img.dtype)


def gaussian_kernel_2d(aperture: int, sigma: float) -> np.ndarray:
    r = np.arange(aperture) - (aperture - 1) / 2.0
    g = np.exp(-(r ** 2) / (2 * sigma * sigma))
    k = np.outer(g, g)
    return k / k.sum()


class ImageTransformer(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """Composable per-image stage list (opencv/ImageTransformer.scala:280).

    Stages are dicts queued by the fluent helpers: resize / crop / colorFormat
    / flip / blur / threshold / gaussianKernel."""

    stages = _p.Param("stages", "ordered image-op specs", None, complex=True)

    def __init__(self, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "image")
        super().__init__(**kw)
        if self.get("stages") is None:
            self.set("stages", [])

    # fluent stage builders (ImageTransformer.scala:310-380 surface)
    def _add(self, spec) -> "ImageTransformer":
        self.set("stages", list(self.get("stages")) + [spec])
        return self

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add({"op": "resize", "height": height, "width": width})

    def crop(self, x: int, y: int, height: int, width: int
             ) -> "ImageTransformer":
        return self._add({"op": "crop", "x": x, "y": y,
                          "height": height, "width": width})

    def color_format(self, fmt: str) -> "ImageTransformer":
        return self._add({"op": "colorFormat", "format": fmt})

    colorFormat = color_format

    def flip(self, flip_left_right: bool = True) -> "ImageTransformer":
        return self._add({"op": "flip", "horizontal": flip_left_right})

    def blur(self, height: int, width: int) -> "ImageTransformer":
        return self._add({"op": "blur", "height": int(height),
                          "width": int(width)})

    def threshold(self, threshold: float, max_val: float = 255.0
                  ) -> "ImageTransformer":
        return self._add({"op": "threshold", "threshold": threshold,
                          "maxVal": max_val})

    def gaussian_kernel(self, aperture_size: int, sigma: float
                        ) -> "ImageTransformer":
        return self._add({"op": "gaussianKernel",
                          "apertureSize": int(aperture_size),
                          "sigma": float(sigma)})

    gaussianKernel = gaussian_kernel

    def _apply(self, img: np.ndarray) -> np.ndarray:
        for spec in self.get("stages"):
            op = spec["op"]
            if op == "resize":
                img = resize_image(img, spec["height"], spec["width"])
            elif op == "crop":
                img = img[spec["y"]:spec["y"] + spec["height"],
                          spec["x"]:spec["x"] + spec["width"]]
            elif op == "colorFormat":
                fmt = spec["format"]
                if fmt in ("gray", "grayscale"):
                    # ITU-R BT.601 luma, assuming RGB channel order
                    img = (img[..., :3] @ np.array([0.299, 0.587, 0.114],
                                                   np.float32))[..., None]
                elif fmt in ("bgr2rgb", "rgb2bgr"):
                    img = img[..., ::-1].copy()
                else:
                    raise ValueError(f"unknown color format {fmt!r}")
            elif op == "flip":
                img = (img[:, ::-1] if spec["horizontal"]
                       else img[::-1]).copy()
            elif op == "blur":
                img = _box_blur(img, spec["height"], spec["width"])
            elif op == "threshold":
                img = np.where(img > spec["threshold"], spec["maxVal"],
                               0.0).astype(img.dtype)
            elif op == "gaussianKernel":
                k = gaussian_kernel_2d(spec["apertureSize"], spec["sigma"])
                import jax
                import jax.numpy as jnp
                pad = spec["apertureSize"] // 2
                padded = np.pad(img, ((pad, k.shape[0] - 1 - pad),
                                      (pad, k.shape[1] - 1 - pad), (0, 0)),
                                mode="edge")
                img = np.asarray(jax.lax.conv_general_dilated(
                    jnp.asarray(padded.transpose(2, 0, 1)[:, None]),
                    jnp.asarray(k[None, None].astype(np.float32)),
                    (1, 1), "VALID")[:, 0].transpose(1, 2, 0))
            else:
                raise ValueError(f"unknown image op {op!r}")
        return img

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("inputCol")]
        out = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            out[i] = self._apply(_as_image(col[i]))
        return df.with_column(self.get("outputCol"), out)


class ResizeImageTransformer(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """Reference: image/ResizeImageTransformer.scala:21-120."""
    height = _p.Param("height", "output height", 224, int)
    width = _p.Param("width", "output width", 224, int)

    def __init__(self, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "image")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("inputCol")]
        h, w = self.get("height"), self.get("width")
        out = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            out[i] = resize_image(_as_image(col[i]), h, w)
        return df.with_column(self.get("outputCol"), out)


class UnrollImage(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """HWC image -> flat CHW float vector (image/UnrollImage.scala:24-201 —
    the CNTK input convention, kept for API parity; DNNModel also accepts
    stacked HWC batches directly)."""

    def __init__(self, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "features")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("inputCol")]
        rows = [_as_image(v).transpose(2, 0, 1).ravel() for v in col]
        return df.with_column(self.get("outputCol"),
                              np.stack(rows).astype(np.float32))


class UnrollBinaryImage(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """Encoded image BYTES -> (optional resize) -> flat CHW float vector in
    one stage (image/UnrollImage.scala `UnrollBinaryImage`: the binary-file
    shortcut that skips the intermediate image column). Rows whose bytes
    fail to decode emit None (the reference's null-passthrough)."""

    height = _p.Param("height", "resize height (0 = keep)", 0, int)
    width = _p.Param("width", "resize width (0 = keep)", 0, int)

    def __init__(self, **kw):
        kw.setdefault("inputCol", "bytes")
        kw.setdefault("outputCol", "features")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        from ...io.files import decode_image
        h, w = self.get("height"), self.get("width")
        out = np.empty(len(df), dtype=object)
        for i, blob in enumerate(df[self.get("inputCol")]):
            img = decode_image(bytes(blob)) if blob is not None else None
            if img is None:
                out[i] = None
                continue
            if h and w and img.shape[:2] != (h, w):
                # the SAME resize as ResizeImageTransformer so the
                # one-stage shortcut is feature-identical to the two-stage
                # pipeline (no train/serve skew between the two routes)
                img = resize_image(img, h, w)
            out[i] = np.asarray(img).transpose(2, 0, 1).ravel().astype(
                np.float32)
        return df.with_column(self.get("outputCol"), out)


class ImageSetAugmenter(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """Emit original + flipped variants (image/ImageSetAugmenter.scala:15-80).
    Output has more rows than input (originals first, then each enabled flip)."""

    flipLeftRight = _p.Param("flipLeftRight", "add LR-flipped copies", True,
                             bool)
    flipUpDown = _p.Param("flipUpDown", "add UD-flipped copies", False, bool)

    def __init__(self, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "image")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("inputCol")]
        originals = np.empty(len(df), dtype=object)
        for i in range(len(df)):  # coerce so all variants share HWC shape
            originals[i] = _as_image(col[i])
        variants: List[DataFrame] = [df.with_column(self.get("outputCol"),
                                                    originals)]
        col = originals
        if self.get("flipLeftRight"):
            flipped = np.empty(len(df), dtype=object)
            for i in range(len(df)):
                flipped[i] = _as_image(col[i])[:, ::-1].copy()
            variants.append(df.with_column(self.get("outputCol"), flipped))
        if self.get("flipUpDown"):
            flipped = np.empty(len(df), dtype=object)
            for i in range(len(df)):
                flipped[i] = _as_image(col[i])[::-1].copy()
            variants.append(df.with_column(self.get("outputCol"), flipped))
        out = variants[0]
        for v in variants[1:]:
            out = out.union(v)
        return out
