"""Expert-parallel (ep x dp) MoE training step.

Completes the distributed-training taxonomy (tp/pp/dp/sp/ep) the TPU build
treats as first-class (no reference analogue — SURVEY.md §2.2/§5: the
reference's parallelism is data-parallel partitions only).

Layout (canonical Switch/TPU): the token batch is sharded over BOTH mesh
axes (data x model) — every device holds a distinct micro-batch; experts
are sharded over the MODEL axis and replicated over DATA; router + head are
replicated everywhere. moe_ffn's two all_to_alls ride the model axis;
expert grads psum over data only, while replicated-param grads psum over
both axes. The whole step (loss, backward, Adam update) runs inside one
shard_map — one compiled SPMD program, matching make_tp_dp_train_step's
stacked-shard calling convention (transformer.py:261-425).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ...parallel.mesh import shard_map as _shard_map
import numpy as np

from ...ops.moe import init_moe_params, moe_ffn, shard_moe_params

__all__ = ["init_moe_block_params", "make_ep_dp_train_step",
           "init_moe_params", "moe_ffn", "shard_moe_params"]


def init_moe_block_params(key, num_experts: int, d_model: int, d_ff: int,
                          num_out: int):
    """One MoE block + mean-pool + linear head — the minimal end-to-end
    trainable MoE model used by tests and the multichip dryrun."""
    ks = jax.random.split(key, 2)
    return {
        "moe": init_moe_params(ks[0], num_experts, d_model, d_ff),
        "head": {"w": jax.random.normal(ks[1], (d_model, num_out))
                 * np.sqrt(1.0 / d_model), "b": jnp.zeros((num_out,))},
    }


def moe_block_loss(params, x, y, num_experts: int, capacity_factor: float,
                   axis_name=None, aux_weight: float = 1e-2):
    """MSE head loss + Switch aux load-balance loss on one MoE block."""
    h, aux = moe_ffn(params["moe"], x, num_experts,
                     capacity_factor=capacity_factor, axis_name=axis_name)
    pooled = h.mean(axis=1)                                   # [B, D]
    pred = pooled @ params["head"]["w"] + params["head"]["b"]
    return jnp.mean((pred - y) ** 2) + aux_weight * aux


def make_ep_dp_train_step(mesh, num_experts: int, learning_rate: float,
                          capacity_factor: float = 4.0,
                          data_axis=None, model_axis=None,
                          optimizer=None):
    """One expert-parallel MoE training step over a 2-D (data, model) mesh.

    Returns (step, shard_params):
      params_s, opt_s = shard_params(full_params)
      params_s, opt_s, loss = step(params_s, opt_s, x, y)
    x: [B, S, D] with B divisible by data*model (tokens sharded over both
    axes); y: [B, num_out]. Fitting runs Adam inside the shard_map; the
    stacked leading axis (= model shards) carries each rank's expert slice,
    peeled to size 1 per device like make_tp_dp_train_step.
    """
    import optax
    from ...parallel import mesh as meshlib
    from jax.sharding import PartitionSpec as P
    data_axis = data_axis or meshlib.DATA_AXIS
    model_axis = model_axis or meshlib.MODEL_AXIS
    ep = mesh.shape[model_axis]
    if num_experts % ep:
        raise ValueError(f"num_experts {num_experts} must divide over the "
                         f"model axis ({ep} shards)")
    tx = optimizer if optimizer is not None else optax.adam(learning_rate)

    def step(params, opt_state, x, y):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        opt_state = jax.tree_util.tree_map(lambda a: a[0], opt_state)
        loss, grads = jax.value_and_grad(moe_block_loss)(
            params, x, y, num_experts, capacity_factor, model_axis)
        # experts are sharded over MODEL (disjoint slices). Every model
        # rank's local loss back-propagates into the expert slices through
        # the all_to_all transpose, so the raw expert grad is already the
        # gradient of the SUM over the model group — divide by ep so
        # experts train on the same MEAN loss as router/head (caught by
        # tests/test_moe.py::test_ep_dp_sgd_grad_scale; Adam's scale
        # invariance hides the mismatch, SGD does not).
        both = lambda g: jax.lax.pmean(
            jax.lax.pmean(g, data_axis), model_axis)
        dp_only = lambda g: jax.lax.pmean(g, data_axis) / ep
        grads = {
            "moe": {"router": jax.tree_util.tree_map(
                        both, grads["moe"]["router"]),
                    "ff1": jax.tree_util.tree_map(
                        dp_only, grads["moe"]["ff1"]),
                    "ff2": jax.tree_util.tree_map(
                        dp_only, grads["moe"]["ff2"])},
            "head": jax.tree_util.tree_map(both, grads["head"]),
        }
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        lift = lambda a: a[None]
        return (jax.tree_util.tree_map(lift, params),
                jax.tree_util.tree_map(lift, opt_state), both(loss))

    sharded = _shard_map(
        step, mesh=mesh,
        in_specs=(P(model_axis), P(model_axis),
                  P((data_axis, model_axis)), P((data_axis, model_axis))),
        out_specs=(P(model_axis), P(model_axis), P()),
        check_vma=False)

    def shard_params(full_params) -> Tuple[dict, tuple]:
        shards = [{"moe": shard_moe_params(full_params["moe"], r, ep),
                   "head": full_params["head"]} for r in range(ep)]
        stack = lambda *xs: jnp.stack(xs)
        stacked = jax.tree_util.tree_map(stack, *shards)
        opt_shards = [tx.init(s) for s in shards]
        return stacked, jax.tree_util.tree_map(stack, *opt_shards)

    return jax.jit(sharded), shard_params
