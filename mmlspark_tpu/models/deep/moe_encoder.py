"""Switch-MoE transformer encoder: every layer's dense FFN replaced by a
top-1-routed expert mixture, trained expert-parallel over the mesh.

Composes the two proven pieces — the shared attention sub-layer
(transformer.encoder_layer's pre-LN attention block) and the
token-dispatching MoE FFN (ops/moe.moe_ffn: capacity buckets + two
all_to_alls riding the model axis) — into a full encoder + classifier
head. No reference analogue (SURVEY §2.2: the reference's parallelism is
data-parallel partitions only); this is the ep leg of the tp/pp/dp/sp/ep
taxonomy at the ESTIMATOR surface (TransformerEncoderClassifier
strategy='moe').

Layout (canonical Switch/TPU, same as models/deep/moe.py): tokens sharded
over BOTH mesh axes, experts sharded over MODEL, attention/LN/router/head
replicated. Expert grads pmean over data / ep; replicated-param grads
pmean over both axes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...parallel.mesh import shard_map as _shard_map
import numpy as np

from ...ops.moe import init_moe_params, moe_ffn, shard_moe_params
from .transformer import _layer_norm, attention_sublayer

__all__ = ["init_moe_encoder_params", "moe_encoder_forward",
           "make_moe_ep_dp_train_step", "unshard_moe_encoder_params"]


def init_moe_encoder_params(key, num_layers: int, d_model: int,
                            num_heads: int, d_ff: int, num_experts: int):
    """Per layer: pre-LN attention (qkv/proj/ln1) + MoE FFN (ln2 + router
    + expert stacks). Attention init matches the dense encoder's
    per-matrix Xavier (init_encoder_params) so strategy='moe' starts from
    the same statistics as every other strategy."""
    def dense(k, fan_in, fan_out):
        scale = np.sqrt(2.0 / (fan_in + fan_out))
        return {"w": jax.random.normal(k, (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,))}

    layers = []
    for i in range(num_layers):
        ks = jax.random.split(jax.random.fold_in(key, i), 3)
        layers.append({
            "qkv": dense(ks[0], d_model, 3 * d_model),
            "proj": dense(ks[1], d_model, d_model),
            "ln1": {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))},
            "ln2": {"g": jnp.ones((d_model,)), "b": jnp.zeros((d_model,))},
            "moe": init_moe_params(ks[2], num_experts, d_model, d_ff),
        })
    return {"layers": layers}


def _moe_layer(x, lp, num_heads: int, num_experts: int,
               capacity_factor: float, causal: bool,
               axis_name: Optional[str]) -> Tuple[jax.Array, jax.Array]:
    """One pre-LN MoE encoder layer; returns (y, aux load-balance loss).
    The attention block is THE shared sublayer (transformer.
    attention_sublayer) with the dense reference path — the training
    convention, the fused flash kernel has no VJP."""
    x = attention_sublayer(x, lp, num_heads, causal=causal,
                           attention_impl="reference")
    h = _layer_norm(x, lp["ln2"])
    y, aux = moe_ffn(lp["moe"], h, num_experts,
                     capacity_factor=capacity_factor, axis_name=axis_name)
    return x + y, aux


def moe_encoder_forward(params, x: jax.Array, num_heads: int,
                        num_experts: int, capacity_factor: float = 2.0,
                        causal: bool = False,
                        axis_name: Optional[str] = None
                        ) -> Tuple[jax.Array, jax.Array]:
    """[B, S, D] -> ([B, S, D], summed aux loss). axis_name set = expert
    shards + local tokens inside shard_map; None = full experts on one
    device (the fitted-model scoring path)."""
    aux_total = jnp.float32(0.0)
    for lp in params["layers"]:
        x, aux = _moe_layer(x, lp, num_heads, num_experts, capacity_factor,
                            causal, axis_name)
        aux_total = aux_total + aux
    return x, aux_total


def unshard_moe_encoder_params(stacked, num_experts: int):
    """Inverse of the per-rank expert slicing: stacked [ep, ...] layer
    pytrees -> full params (expert stacks concatenated along the expert
    axis; replicated leaves take rank 0). num_experts validates the
    reassembled expert count."""
    layers_out = []
    n_layers = len(stacked["layers"])
    for li in range(n_layers):
        lp = stacked["layers"][li]
        out = {k: jax.tree_util.tree_map(lambda a: np.asarray(a)[0], lp[k])
               for k in ("qkv", "proj", "ln1", "ln2")}
        moe = lp["moe"]
        out["moe"] = {
            "router": jax.tree_util.tree_map(
                lambda a: np.asarray(a)[0], moe["router"]),
            "ff1": jax.tree_util.tree_map(
                lambda a: np.concatenate(np.asarray(a), axis=0), moe["ff1"]),
            "ff2": jax.tree_util.tree_map(
                lambda a: np.concatenate(np.asarray(a), axis=0), moe["ff2"]),
        }
        got = out["moe"]["ff1"]["w"].shape[0]
        if got != num_experts:
            raise ValueError(
                f"layer {li}: reassembled {got} experts, expected "
                f"{num_experts}")
        layers_out.append(out)
    return {"layers": layers_out}


def make_moe_ep_dp_train_step(mesh, num_heads: int, learning_rate: float,
                              num_classes: int, num_experts: int,
                              capacity_factor: float = 2.0,
                              causal: bool = False,
                              aux_weight: float = 1e-2,
                              data_axis: Optional[str] = None,
                              model_axis: Optional[str] = None):
    """One expert-parallel MoE-encoder training step over a 2-D mesh.

    Returns (step, shard_params) with make_tp_dp_train_step's stacked
    calling convention. x: [B, S, D], B divisible by data*model shards
    (tokens ride both axes); y: [B] int labels.
    """
    import optax
    from ...parallel import mesh as meshlib
    from jax.sharding import PartitionSpec as P
    data_axis = data_axis or meshlib.DATA_AXIS
    model_axis = model_axis or meshlib.MODEL_AXIS
    ep = mesh.shape[model_axis]
    if num_experts % ep:
        raise ValueError(f"num_experts {num_experts} must divide over the "
                         f"model axis ({ep} shards)")
    tx = optax.adam(learning_rate)

    def loss_fn(params, x, y):
        enc, aux = moe_encoder_forward(
            params["encoder"], x, num_heads, num_experts, capacity_factor,
            causal, axis_name=model_axis)
        pooled = enc.mean(axis=1)
        logits = pooled @ params["head"]["w"] + params["head"]["b"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.mean(jnp.sum(jax.nn.one_hot(y, num_classes) * logp,
                               axis=-1))
        return ce + aux_weight * aux

    def _split(tree_fn_expert, tree_fn_repl, grads):
        out_layers = []
        for lp in grads["encoder"]["layers"]:
            g = {k: jax.tree_util.tree_map(tree_fn_repl, lp[k])
                 for k in ("qkv", "proj", "ln1", "ln2")}
            g["moe"] = {
                "router": jax.tree_util.tree_map(tree_fn_repl,
                                                 lp["moe"]["router"]),
                "ff1": jax.tree_util.tree_map(tree_fn_expert,
                                              lp["moe"]["ff1"]),
                "ff2": jax.tree_util.tree_map(tree_fn_expert,
                                              lp["moe"]["ff2"]),
            }
            out_layers.append(g)
        return {"encoder": {"layers": out_layers},
                "head": jax.tree_util.tree_map(tree_fn_repl, grads["head"])}

    def step(params, opt_state, x, y):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        opt_state = jax.tree_util.tree_map(lambda a: a[0], opt_state)
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        # expert slices are disjoint over MODEL: their raw grad is already
        # the model-group sum — /ep puts them on the same MEAN loss as the
        # replicated params (models/deep/moe.py's SGD-exposed convention)
        both = lambda g: jax.lax.pmean(
            jax.lax.pmean(g, data_axis), model_axis)
        dp_only = lambda g: jax.lax.pmean(g, data_axis) / ep
        grads = _split(dp_only, both, grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        lift = lambda a: a[None]
        return (jax.tree_util.tree_map(lift, params),
                jax.tree_util.tree_map(lift, opt_state), both(loss))

    sharded = _shard_map(
        step, mesh=mesh,
        in_specs=(P(model_axis), P(model_axis),
                  P((data_axis, model_axis)), P((data_axis, model_axis))),
        out_specs=(P(model_axis), P(model_axis), P()),
        check_vma=False)

    def shard_params(full_params, head):
        shards = []
        for r in range(ep):
            layers = []
            for lp in full_params["layers"]:
                layers.append({
                    **{k: lp[k] for k in ("qkv", "proj", "ln1", "ln2")},
                    "moe": shard_moe_params(lp["moe"], r, ep),
                })
            shards.append({"encoder": {"layers": layers}, "head": head})
        stack = lambda *xs: jnp.stack(xs)
        stacked = jax.tree_util.tree_map(stack, *shards)
        opt_shards = [tx.init(s) for s in shards]
        return stacked, jax.tree_util.tree_map(stack, *opt_shards)

    return jax.jit(sharded), shard_params
