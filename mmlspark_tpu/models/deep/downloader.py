"""Remote model repository with retry/timeout, cache, and checksums.

Reference: downloader/ModelDownloader.scala:27-250 — `Repository[S]` over a
remote model zoo with a schema file, `FaultToleranceUtils.retryWithTimeout`
(:37-52) around every fetch, and local caching; downloader/Schema.scala for
the per-model metadata (layerNames, inputNode, dims, uri, hash).

TPU restructure: models are flax checkpoints (npz of leaves, resnet.py
save_params layout) instead of CNTK .model protobufs; the repository is any
HTTP endpoint serving `MANIFEST.json` + checkpoint files. Checksums are
sha256 (the reference records a hash per model in its schema).
"""

from __future__ import annotations

import hashlib
import json
import os
import urllib.request
from typing import Callable, Dict, List, TypeVar

from ...resilience import RetryPolicy

T = TypeVar("T")


def retry_with_timeout(fn: Callable[[], T], timeout_s: float = 60.0,
                       retries: int = 3, backoff_s: float = 0.5) -> T:
    """FaultToleranceUtils.retryWithTimeout (:37-52): run fn with a hard
    per-attempt timeout, retrying with backoff on failure OR timeout.

    Thin shim over the shared `resilience.RetryPolicy` (which owns the
    abandoned-executor hard-timeout mechanics) kept so existing imports
    keep working; new code should construct a RetryPolicy directly."""
    return RetryPolicy(attempts=retries, timeout_s=timeout_s,
                       backoff_s=backoff_s, multiplier=2.0,
                       jitter=0.1).call(fn)


class RemoteModelInfo:
    """One manifest entry (downloader/Schema.scala fields that survive the
    format change)."""

    __slots__ = ("name", "uri", "sha256", "size", "input_dims")

    def __init__(self, name: str, uri: str, sha256: str = "",
                 size: int = 0, input_dims=None):
        self.name = name
        self.uri = uri
        self.sha256 = sha256
        self.size = size
        self.input_dims = input_dims

    @staticmethod
    def from_dict(d: Dict) -> "RemoteModelInfo":
        return RemoteModelInfo(d["name"], d["uri"], d.get("sha256", ""),
                               int(d.get("size", 0)), d.get("inputDims"))


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class RemoteRepository:
    """HTTP model zoo: MANIFEST.json + checkpoint files, cached locally.

    The remote `Repository[S]` / `DefaultModelRepo` analogue
    (ModelDownloader.scala:27-250): list models from the manifest, download
    with retry+timeout, verify sha256, serve from cache when already present
    and intact."""

    def __init__(self, base_url: str, cache_dir: str,
                 timeout_s: float = 60.0, retries: int = 3):
        self.base_url = base_url.rstrip("/")
        self.cache_dir = cache_dir
        self.timeout_s = timeout_s
        self.retries = retries
        os.makedirs(cache_dir, exist_ok=True)

    # -------------------------------------------------------------- manifest
    def models(self) -> List[RemoteModelInfo]:
        def fetch():
            with urllib.request.urlopen(self.base_url + "/MANIFEST.json",
                                        timeout=self.timeout_s) as r:
                return [RemoteModelInfo.from_dict(d)
                        for d in json.loads(r.read())]
        return retry_with_timeout(fetch, self.timeout_s, self.retries)

    def model_info(self, name: str) -> RemoteModelInfo:
        for m in self.models():
            if m.name == name:
                return m
        raise KeyError(f"model {name!r} not in repository "
                       f"{self.base_url}")

    # -------------------------------------------------------------- download
    def _cache_path(self, info: RemoteModelInfo) -> str:
        # keyed by model name + uri digest: distinct models whose URIs share
        # a basename (r18/model.npz vs r50/model.npz) must not collide
        ext = os.path.splitext(info.uri)[1] or ".npz"
        tag = hashlib.sha256(info.uri.encode()).hexdigest()[:12]
        return os.path.join(self.cache_dir, f"{info.name}-{tag}{ext}")

    def download_model(self, name: str) -> str:
        """Fetch a model checkpoint; returns the local path. Cached files
        with a matching checksum are reused without touching the network."""
        info = self.model_info(name)
        dest = self._cache_path(info)
        if os.path.exists(dest):
            if not info.sha256 or _sha256(dest) == info.sha256:
                return dest
            os.remove(dest)  # corrupt cache entry: refetch

        url = (info.uri if info.uri.startswith(("http://", "https://"))
               else f"{self.base_url}/{info.uri.lstrip('/')}")

        def fetch():
            tmp = dest + ".part"
            with urllib.request.urlopen(url, timeout=self.timeout_s) as r, \
                    open(tmp, "wb") as f:
                while True:
                    chunk = r.read(1 << 20)
                    if not chunk:
                        break
                    f.write(chunk)
            if info.sha256 and _sha256(tmp) != info.sha256:
                os.remove(tmp)
                raise IOError(f"checksum mismatch for {name!r}")
            os.replace(tmp, dest)
            return dest

        return retry_with_timeout(fetch, self.timeout_s, self.retries)
