"""Deep-learning inference path (reference: cntk/ + image/ + opencv/ +
downloader/). The CNTK JNI eval engine becomes a jitted flax forward pass."""

from .dnn import DNNModel, GraphModel, ImageFeaturizer
from .image import (ImageSetAugmenter, ImageTransformer,
                    ResizeImageTransformer, UnrollBinaryImage, UnrollImage)
from .resnet import ModelDownloader, ModelSchema, ResNet, load_params, save_params
from .transformer import (TransformerClassificationModel,
                          TransformerEncoderClassifier,
                          TransformerEncoderModel, encoder_forward,
                          init_encoder_params, init_head_params,
                          make_tp_dp_train_step)
from .pipeline import make_pp_dp_train_step, pipeline_forward
from .moe import (init_moe_block_params, make_ep_dp_train_step, moe_ffn,
                  init_moe_params)
from .checkpoint import (latest_step, restore_train_state, save_train_state)
from .moe_encoder import (init_moe_encoder_params, make_moe_ep_dp_train_step,
                          moe_encoder_forward, unshard_moe_encoder_params)

__all__ = [
    "make_pp_dp_train_step", "pipeline_forward",
    "make_ep_dp_train_step", "moe_ffn", "init_moe_params",
    "init_moe_block_params",
    "DNNModel", "GraphModel", "ImageFeaturizer",
    "ImageTransformer", "ResizeImageTransformer", "UnrollImage",
    "UnrollBinaryImage",
    "ImageSetAugmenter",
    "ResNet", "ModelDownloader", "ModelSchema", "load_params", "save_params",
    "TransformerEncoderModel", "encoder_forward", "init_encoder_params",
    "init_head_params",
    "TransformerEncoderClassifier", "TransformerClassificationModel",
    "make_tp_dp_train_step",
    "save_train_state", "restore_train_state", "latest_step",
    "init_moe_encoder_params", "moe_encoder_forward",
    "make_moe_ep_dp_train_step", "unshard_moe_encoder_params",
]
