"""Deep-learning inference path (reference: cntk/ + image/ + opencv/ +
downloader/). The CNTK JNI eval engine becomes a jitted flax forward pass."""

from .dnn import DNNModel, GraphModel, ImageFeaturizer
from .image import (ImageSetAugmenter, ImageTransformer,
                    ResizeImageTransformer, UnrollBinaryImage, UnrollImage)
from .resnet import ModelDownloader, ModelSchema, ResNet, load_params, save_params
from .transformer import (TransformerClassificationModel,
                          TransformerEncoderClassifier,
                          TransformerEncoderModel, encoder_forward,
                          init_encoder_params, make_tp_dp_train_step)

__all__ = [
    "DNNModel", "GraphModel", "ImageFeaturizer",
    "ImageTransformer", "ResizeImageTransformer", "UnrollImage",
    "UnrollBinaryImage",
    "ImageSetAugmenter",
    "ResNet", "ModelDownloader", "ModelSchema", "load_params", "save_params",
    "TransformerEncoderModel", "encoder_forward", "init_encoder_params",
    "TransformerEncoderClassifier", "TransformerClassificationModel",
    "make_tp_dp_train_step",
]
