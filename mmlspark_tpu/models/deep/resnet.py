"""ResNet in flax + the local model zoo.

Reference: the CNTK model zoo reached through downloader/ModelDownloader.scala
:27-250 (remote `Repository[S]` of serialized CNTK graphs with schema —
layerNames, inputNode, dims) whose flagship entry is ResNet-50 for
ImageFeaturizer. Here models are flax modules with locally materialized
parameters (zero-egress environment: weights initialize deterministically from
a seed; `load_params` accepts externally supplied checkpoints via orbax/npz).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    projection: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        y = nn.BatchNorm(use_running_average=True)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False)(y)
        y = nn.BatchNorm(use_running_average=True)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = nn.BatchNorm(use_running_average=True, scale_init=nn.initializers.zeros)(y)
        if self.projection:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False)(x)
            residual = nn.BatchNorm(use_running_average=True)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet-v1.5 (bottleneck). stage_sizes (3,4,6,3) = ResNet-50."""
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x, train: bool = False, capture=None):
        feats = {}
        x = nn.Conv(64, (7, 7), (2, 2), use_bias=False, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=True)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), "SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(64 * 2 ** i, strides,
                                    projection=(j == 0))(x)
            feats[f"stage{i + 1}"] = x
        x = x.mean(axis=(1, 2))
        feats["pool"] = x  # penultimate features (the ImageFeaturizer cut)
        x = nn.Dense(self.num_classes, name="head")(x)
        feats["logits"] = x
        if capture is not None:
            return feats[capture]
        return x


class ModelSchema:
    """Zoo entry metadata (downloader/Schema.scala: layerNames, inputNode,
    dims)."""

    def __init__(self, name: str, module: nn.Module,
                 input_dims: Tuple[int, int, int],
                 layer_names: Sequence[str],
                 mean: Sequence[float], std: Sequence[float]):
        self.name = name
        self.module = module
        self.input_dims = input_dims    # (H, W, C)
        self.layer_names = list(layer_names)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)


_ZOO: Dict[str, Callable[[], ModelSchema]] = {
    "ResNet50": lambda: ModelSchema(
        "ResNet50", ResNet(stage_sizes=(3, 4, 6, 3)), (224, 224, 3),
        ["stage1", "stage2", "stage3", "stage4", "pool", "logits"],
        mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    "ResNet18-ish": lambda: ModelSchema(
        # bottleneck variant at ResNet-18 depth budget (for fast tests)
        "ResNet18-ish", ResNet(stage_sizes=(1, 1, 1, 1)), (64, 64, 3),
        ["stage1", "stage2", "stage3", "stage4", "pool", "logits"],
        mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    "ResNet-Digits": lambda: ModelSchema(
        # the BUNDLED pretrained anchor (scripts/train_zoo_checkpoint.py):
        # two-stage bottleneck trained on sklearn digits 16x16x3 to the
        # accuracy recorded in zoo/MANIFEST.json — the quality anchor the
        # reference gets from its CNTK zoo (ModelDownloader.scala:27-250)
        "ResNet-Digits", ResNet(stage_sizes=(1, 1), num_classes=10),
        (16, 16, 3), ["stage1", "stage2", "pool", "logits"],
        mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    "ResNet-DigitsClutter32": lambda: ModelSchema(
        # the HARDER bundled anchor (scripts/train_zoo_checkpoint2.py):
        # twice the block depth, 32x32 input, trained on the
        # DigitsClutter-32 task (random digit placement + distractor
        # fragments + noise) — the transfer-quality anchor for the full
        # image-bytes path (decode->resize->unroll->featurize->train)
        "ResNet-DigitsClutter32", ResNet(stage_sizes=(2, 2), num_classes=10),
        (32, 32, 3), ["stage1", "stage2", "pool", "logits"],
        mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
}

_BUNDLED_ZOO_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "zoo")


def bundled_zoo_url() -> str:
    """file:// URL of the in-repo pretrained-checkpoint zoo — served through
    RemoteRepository so manifest + sha256 + caching run on the same code
    path a remote zoo would use."""
    return "file://" + _BUNDLED_ZOO_DIR


class ModelDownloader:
    """Zoo resolver (ModelDownloader.scala:27-250). Weight sources, in
    precedence order: a remote repository (repo_url -> RemoteRepository
    with retry/timeout, cache, sha256 — downloader.py), a local checkpoint
    (local_path), the BUNDLED in-repo zoo (models listed in
    zoo/MANIFEST.json, served through the same RemoteRepository mechanism
    via file://; `seed` is ignored for bundled weights), or the
    deterministic seed init (pretrained=False, or no source has the
    model)."""

    def __init__(self, local_path: Optional[str] = None,
                 repo_url: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 timeout_s: float = 60.0, retries: int = 3):
        import tempfile
        self.local_path = local_path
        self.cache_dir = cache_dir or os.path.join(
            tempfile.gettempdir(), "mmlspark_tpu_models")
        self.timeout_s = timeout_s
        self.retries = retries
        self.repo = None
        if repo_url:
            self.repo = self._make_repo(repo_url)

    def _make_repo(self, url: str):
        from .downloader import RemoteRepository
        return RemoteRepository(url, self.cache_dir,
                                timeout_s=self.timeout_s,
                                retries=self.retries)

    def _bundled_checkpoint(self, name: str) -> Optional[str]:
        """Path to a bundled pretrained checkpoint, or None. Membership is
        checked against the local manifest first (plain json read) so
        non-bundled models never pay a repository round-trip."""
        import json
        manifest = os.path.join(_BUNDLED_ZOO_DIR, "MANIFEST.json")
        if not os.path.exists(manifest):
            return None
        with open(manifest) as f:
            names = {m["name"] for m in json.load(f)}
        if name not in names:
            return None
        return self._make_repo(bundled_zoo_url()).download_model(name)

    def list_models(self) -> Sequence[str]:
        if self.repo is not None:
            return sorted(m.name for m in self.repo.models())
        return sorted(_ZOO)

    def download_by_name(self, name: str, seed: int = 0,
                         pretrained: bool = True):
        """pretrained=False skips every weight source (remote repo, local
        checkpoint, bundled zoo) and returns the deterministic seed init —
        the from-scratch baseline for transfer-learning comparisons."""
        from .dnn import GraphModel
        if name not in _ZOO:
            raise KeyError(f"unknown model {name!r}; have {sorted(_ZOO)}")
        schema = _ZOO[name]()
        h, w, c = schema.input_dims
        variables = schema.module.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, h, w, c), jnp.float32))
        if pretrained:
            if self.repo is not None:
                variables = load_params(self.repo.download_model(name),
                                        variables)
            elif self.local_path:
                variables = load_params(self.local_path, variables)
            else:
                ckpt = self._bundled_checkpoint(name)
                if ckpt:
                    variables = load_params(ckpt, variables)
        return GraphModel(module=schema.module, variables=variables,
                          schema=schema)

    downloadByName = download_by_name


def _npz_path(path: str) -> str:
    return path if path.endswith(".npz") else path + ".npz"


def load_params(path: str, template):
    """Load a checkpoint saved as npz of flattened paths onto a template
    pytree."""
    flat = np.load(_npz_path(path))
    leaves, treedef = jax.tree.flatten(template)
    keys = sorted(flat.files)
    if len(keys) != len(leaves):
        raise ValueError(f"checkpoint has {len(keys)} arrays, "
                         f"model expects {len(leaves)}")
    loaded = []
    for k, leaf in zip(keys, leaves):
        arr = flat[k]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint array {k} has shape {arr.shape}, model leaf "
                f"expects {np.shape(leaf)} — wrong architecture?")
        loaded.append(arr)
    return jax.tree.unflatten(treedef, loaded)


def save_params(path: str, variables) -> None:
    leaves, _ = jax.tree.flatten(variables)
    np.savez(_npz_path(path), **{f"p{i:05d}": np.asarray(l)
                                 for i, l in enumerate(leaves)})
