"""Featurize / AssembleFeatures — type-dispatched feature assembly.

Reference: featurize/Featurize.scala:25-113 -> featurize/AssembleFeatures.scala:96-462:
numeric passthrough (+ missing replacement), string hashing (2^18 default / 2^12 when
feeding tree learners — Featurize.scala:17-20), categorical one-hot via column
metadata, image unroll; then assembly into one dense vector column. Output is a dense
float32 matrix — the TPU-native feature format (HBM wants dense tiles; the reference's
SparseVector output exists because of JVM memory pressure, not algorithmic need).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model
from ..utils.hashing import hash_strings
from .indexers import CATEGORICAL_META_KEY

ONE_HOT_MAX = 64  # above this many levels, hash instead of one-hot
# dense hashed output is capped at 2^14 columns per string column: the
# reference's 2^18 default exists for SPARSE vectors (JVM memory pressure);
# a dense TPU feature tile at 2^18 x rows would be HBM-hostile
HASH_BITS_CAP = 14


class Featurize(Estimator):
    """Merge input columns into a single assembled features vector column.

    Reference: featurize/Featurize.scala:25-113."""
    inputCols = _p.Param("inputCols", "columns to featurize", None)
    outputCol = _p.Param("outputCol", "assembled features column", "features")
    numberOfFeatures = _p.Param(
        "numberOfFeatures",
        "hash-space size for high-cardinality string columns (2^18 default, "
        "2^12 for trees — Featurize.scala:17-20); dense output caps the "
        "effective width at 2^14 per column (HASH_BITS_CAP)", 1 << 18, int)
    oneHotEncodeCategoricals = _p.Param(
        "oneHotEncodeCategoricals", "one-hot metadata categoricals", True, bool)
    allowImages = _p.Param("allowImages", "featurize image columns", False, bool)

    def _fit(self, df: DataFrame) -> "FeaturizeModel":
        cols = self.get("inputCols") or [c for c in df.columns]
        plan: List[Dict] = []
        for name in cols:
            col = df[name]
            meta = df.metadata(name)
            if meta.get("is_categorical") and self.get("oneHotEncodeCategoricals"):
                n_levels = len(meta.get(CATEGORICAL_META_KEY, []))
                if n_levels <= ONE_HOT_MAX:
                    plan.append({"col": name, "kind": "onehot", "n": n_levels})
                    continue
            if col.ndim == 2:
                plan.append({"col": name, "kind": "vector", "n": col.shape[1]})
            elif np.issubdtype(col.dtype, np.datetime64):
                # calendar expansion (AssembleFeatures.scala:374-398):
                # date -> [epoch_ms, year, ISO day-of-week, month, day];
                # timestamp adds [hour, minute, second]. Day-resolution
                # columns are dates, finer resolutions are timestamps.
                is_date = np.datetime_data(col.dtype)[0] in ("D", "W", "M",
                                                             "Y")
                plan.append({"col": name,
                             "kind": "date" if is_date else "timestamp",
                             "n": 5 if is_date else 8})
            elif col.dtype == object and len(col) and isinstance(col[0], str):
                # low-cardinality strings: one-hot over observed levels beats
                # hashing (the reference hashes into a 2^18 SPARSE vector —
                # AssembleFeatures.scala:96-462; dense TPU tiles want narrow).
                # Missing values encode as the all-zeros row.
                levels = sorted({v for v in col.tolist()
                                 if isinstance(v, str)})
                if len(levels) <= ONE_HOT_MAX:
                    if self.get("oneHotEncodeCategoricals"):
                        plan.append({"col": name, "kind": "levels",
                                     "levels": levels, "n": len(levels)})
                    else:
                        # single ordinal index column (the reference keeps the
                        # categorical index when one-hot is off —
                        # AssembleFeatures.scala categorical handling)
                        plan.append({"col": name, "kind": "ordinal",
                                     "levels": levels, "n": 1})
                    continue
                nf = int(self.get("numberOfFeatures"))
                bits = min(max(1, int(np.log2(nf))), HASH_BITS_CAP)
                plan.append({"col": name, "kind": "hash", "bits": bits,
                             "n": 1 << bits})
            else:
                v = np.asarray(col, np.float64)
                finite = v[np.isfinite(v)]
                fill = float(finite.mean()) if len(finite) else 0.0
                plan.append({"col": name, "kind": "numeric", "n": 1, "fill": fill})
        model = FeaturizeModel(plan=plan)
        model.set("outputCol", self.get("outputCol"))
        return model


def _calendar_parts(col, with_time: bool) -> np.ndarray:
    """Expand a datetime64 column into the reference's calendar features
    (AssembleFeatures.scala:374-398): [epoch_ms, year, ISO day-of-week
    (Mon=1..Sun=7), month, day-of-month] (+ [hour, minute, second] for
    timestamps). NaT rows encode as all-zeros (the date analogue of the
    numeric path's missing handling — int64-min garbage must never leak
    into the feature matrix). Note the assembled output is float32, which
    quantizes modern epoch_ms values to ~131 s granularity; the calendar
    part slots are exact, and downstream GBDT binning is insensitive to
    the epoch quantization."""
    t = np.asarray(col)
    nat = np.isnat(t)
    t = np.where(nat, np.datetime64(0, np.datetime_data(t.dtype)[0]), t)
    ms = t.astype("datetime64[ms]").astype(np.int64)
    days = t.astype("datetime64[D]").astype(np.int64)
    years = t.astype("datetime64[Y]").astype(np.int64) + 1970
    months = t.astype("datetime64[M]").astype(np.int64) % 12 + 1
    month_start = t.astype("datetime64[M]").astype("datetime64[D]")
    dom = (t.astype("datetime64[D]") - month_start).astype(np.int64) + 1
    dow = (days + 3) % 7 + 1                      # 1970-01-01 was Thursday=4
    cols = [ms.astype(np.float64), years, dow, months, dom]
    if with_time:
        sec_of_day = (t.astype("datetime64[s]").astype(np.int64)
                      - days * 86400)
        cols += [sec_of_day // 3600, sec_of_day // 60 % 60, sec_of_day % 60]
    out = np.stack([np.asarray(c, np.float64) for c in cols],
                   axis=1).astype(np.float32)
    out[nat] = 0.0
    return out


def _lookup_levels(col, levels_list):
    """Map a string column onto sorted levels. Returns (index, valid) where
    valid is False for missing/non-string/unseen values — a separate mask so
    missing never collides with a genuine empty-string level."""
    levels = np.asarray(levels_list, dtype=object)
    present = np.array([isinstance(v, str) for v in col], bool)
    strs = np.array([v if isinstance(v, str) else "" for v in col],
                    dtype=object)
    j = np.searchsorted(levels.astype(str), strs.astype(str))
    j = np.clip(j, 0, len(levels) - 1)
    valid = present & (levels[j] == strs)
    return j, valid


class FeaturizeModel(Model):
    outputCol = _p.Param("outputCol", "assembled features column", "features")
    plan = _p.Param("plan", "per-column encoding plan", None, complex=True)

    def __init__(self, plan: Optional[List[Dict]] = None, **kw):
        super().__init__(**kw)
        if plan is not None:
            self.set("plan", plan)

    def transform(self, df: DataFrame) -> DataFrame:
        parts: List[np.ndarray] = []
        n = len(df)
        for spec in self.get("plan"):
            col = df[spec["col"]]
            kind = spec["kind"]
            if kind == "numeric":
                v = np.asarray(col, np.float64).copy()
                v[~np.isfinite(v)] = spec["fill"]
                parts.append(v[:, None].astype(np.float32))
            elif kind == "vector":
                parts.append(np.asarray(col, np.float32))
            elif kind == "onehot":
                idx = np.asarray(col, np.int64)
                out = np.zeros((n, spec["n"]), np.float32)
                valid = (idx >= 0) & (idx < spec["n"])
                out[np.flatnonzero(valid), idx[valid]] = 1.0
                parts.append(out)
            elif kind == "levels":
                j, valid = _lookup_levels(col, spec["levels"])
                out = np.zeros((n, spec["n"]), np.float32)  # invalid: all-zero
                out[np.flatnonzero(valid), j[valid].astype(np.int64)] = 1.0
                parts.append(out)
            elif kind == "ordinal":
                j, valid = _lookup_levels(col, spec["levels"])
                out = np.where(valid, j.astype(np.float32), -1.0)
                parts.append(out[:, None].astype(np.float32))
            elif kind == "hash":
                buckets = hash_strings([str(s) for s in col], spec["bits"])
                out = np.zeros((n, spec["n"]), np.float32)
                out[np.arange(n), buckets] += 1.0
                parts.append(out)
            elif kind in ("date", "timestamp"):
                parts.append(_calendar_parts(col, kind == "timestamp"))
            else:
                raise ValueError(f"unknown encoding kind {kind!r}")
        assembled = np.concatenate(parts, axis=1) if parts else np.zeros((n, 0),
                                                                         np.float32)
        return df.with_column(self.get("outputCol"), assembled)
