"""Value indexing — categorical <-> index codecs.

Reference: featurize/ValueIndexer.scala:55-187 (`ValueIndexer`/`ValueIndexerModel`
with null ordering), featurize/IndexToValue.scala, and the categorical-metadata
convention of core/schema/Categoricals.scala:17-314 (levels stored as column metadata
so downstream stages — one-hot, LightGBM categorical splits — can recover them).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model, Transformer

CATEGORICAL_META_KEY = "ml_attr_levels"  # categorical levels metadata key


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    return False


class ValueIndexer(Estimator):
    """Learn distinct values of a column -> contiguous indices.

    Null ordering follows the reference (ValueIndexer.scala:55-187): missing values
    sort first (index 0) when present; remaining levels sorted ascending."""
    inputCol = _p.Param("inputCol", "column to index", "input")
    outputCol = _p.Param("outputCol", "indexed output column", "output")

    def _fit(self, df: DataFrame) -> "ValueIndexerModel":
        col = df[self.get("inputCol")]
        has_missing = any(_is_missing(v) for v in col)
        present = [v.item() if hasattr(v, "item") else v
                   for v in col if not _is_missing(v)]
        levels: List[Any] = sorted(set(present))
        if has_missing:
            levels = [None] + levels
        model = ValueIndexerModel(levels=levels)
        model.set("inputCol", self.get("inputCol"))
        model.set("outputCol", self.get("outputCol"))
        return model


class ValueIndexerModel(Model):
    inputCol = _p.Param("inputCol", "column to index", "input")
    outputCol = _p.Param("outputCol", "indexed output column", "output")
    levels = _p.Param("levels", "ordered distinct values", None, complex=True)

    def __init__(self, levels: Optional[List[Any]] = None, **kw):
        super().__init__(**kw)
        if levels is not None:
            self.set("levels", list(levels))

    def transform(self, df: DataFrame) -> DataFrame:
        levels = self.get("levels")
        lookup = {v: i for i, v in enumerate(levels)}
        missing_idx = lookup.get(None, -1)
        col = df[self.get("inputCol")]
        out = np.empty(len(col), dtype=np.int64)
        for i, v in enumerate(col):
            if _is_missing(v):
                out[i] = missing_idx
            else:
                out[i] = lookup.get(v.item() if hasattr(v, "item") else v, -1)
        return df.with_column(
            self.get("outputCol"), out,
            metadata={CATEGORICAL_META_KEY: list(levels),
                      "is_categorical": True})


class IndexToValue(Transformer):
    """Inverse of ValueIndexerModel using the levels stored in column metadata.

    Reference: featurize/IndexToValue.scala."""
    inputCol = _p.Param("inputCol", "indexed column", "input")
    outputCol = _p.Param("outputCol", "decoded output column", "output")

    def transform(self, df: DataFrame) -> DataFrame:
        meta = df.metadata(self.get("inputCol"))
        levels = meta.get(CATEGORICAL_META_KEY)
        if levels is None:
            raise ValueError(
                f"column {self.get('inputCol')!r} has no categorical metadata")
        col = df[self.get("inputCol")].astype(np.int64)
        out = np.empty(len(col), dtype=object)
        for i, idx in enumerate(col):
            out[i] = levels[idx] if 0 <= idx < len(levels) else None
        return df.with_column(self.get("outputCol"), out)
