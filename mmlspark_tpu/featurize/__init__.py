"""Featurization layer (reference: featurize/ — SURVEY.md §2.3, 1757 LoC)."""

from .bundling import SparseFeatureBundler, SparseFeatureBundlerModel
from .clean import CleanMissingData, CleanMissingDataModel, DataConversion
from .featurize import Featurize, FeaturizeModel
from .indexers import (CATEGORICAL_META_KEY, IndexToValue, ValueIndexer,
                       ValueIndexerModel)
from .text import MultiNGram, PageSplitter, TextFeaturizer, TextFeaturizerModel

__all__ = [
    "SparseFeatureBundler",
    "SparseFeatureBundlerModel",
    "CATEGORICAL_META_KEY", "CleanMissingData", "CleanMissingDataModel",
    "DataConversion", "Featurize", "FeaturizeModel", "IndexToValue",
    "MultiNGram", "PageSplitter", "TextFeaturizer", "TextFeaturizerModel",
    "ValueIndexer", "ValueIndexerModel",
]
