"""Exclusive sparse-feature bundling — dense bundles for the MXU histogram.

Reference analogue: SURVEY.md §7 flags "sparse/CSR ingestion ... bin-packing
sparse features" as a hard part of the LightGBM-equivalent data plane
(LGBM_DatasetCreateFromCSRSpark, lightgbm/LightGBMUtils.scala:201-265 CSR
marshalling). Upstream LightGBM solves it internally with Exclusive Feature
Bundling (EFB, the LightGBM paper §4): features that are (almost) never
nonzero on the same row are packed into one column of disjoint bin ranges.

TPU-first adaptation: bundling is a PIPELINE STAGE, not a trainer internal.
Each bundle becomes one dense int32 CATEGORY column (code 0 = all features
zero; feature j's nonzero value binned to b => offset_j + b), and the stage
exports `categoricalSlotIndexes` so a downstream LightGBM trainer searches
subset splits over the bundle — strictly more expressive than per-feature
thresholds for the binary/sparse features this targets, and the histogram
kernel sees a dense narrow matrix instead of a 2^18-wide sparse one. A
hashed-text matrix (featurize/text.py, 2^18 columns) becomes ~max-row-nnz
dense columns.

Greedy bundling follows the EFB algorithm: order features by nonzero count,
place each into the first bundle where added conflicts stay within
`maxConflictRate * n_rows` (and the bundle's bin budget), else open a new
bundle.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model


def _to_csc(x):
    """Accept dense [N, F], scipy CSR/CSC, or a column of per-row sparse
    vectors; return (csc_matrix, n, f)."""
    try:
        import scipy.sparse as sp
    except ImportError:  # pragma: no cover - scipy ships with sklearn
        sp = None
    if sp is not None and sp.issparse(x):
        csc = x.tocsc()
        return csc, csc.shape[0], csc.shape[1]
    x = np.asarray(x)
    if x.dtype == object and len(x) and hasattr(x[0], "toarray"):
        import scipy.sparse as sp
        rows = [r.tocsr() if sp.issparse(r) else sp.csr_matrix(np.asarray(r))
                for r in x]
        csc = sp.vstack(rows).tocsc()
        return csc, csc.shape[0], csc.shape[1]
    if sp is None:
        raise ImportError("scipy required for sparse bundling")
    csc = sp.csc_matrix(x)
    return csc, x.shape[0], x.shape[1]


def greedy_bundles(row_sets, n_rows: int, nnz: np.ndarray,
                   max_conflict_rate: float, bins_per_feature: np.ndarray,
                   max_bundle_bins: int) -> List[List[int]]:
    """EFB greedy packing. row_sets maps (or lists) feature -> row indices
    where it is nonzero; features with nnz == 0 need no entry. Returns
    bundles as lists of original feature indices."""
    order = np.argsort(-nnz, kind="stable")
    budget = max(int(max_conflict_rate * n_rows), 0)
    bundles: List[List[int]] = []
    occupied: List[np.ndarray] = []   # [n_rows] bool per bundle: O(nnz_j)
    bundle_conflicts: List[int] = []  # conflict checks, not O(N log N) set ops
    bundle_bins: List[int] = []
    for j in order:
        if nnz[j] == 0:
            continue  # never-nonzero features contribute nothing
        placed = False
        for bi in range(len(bundles)):
            if bundle_bins[bi] + bins_per_feature[j] > max_bundle_bins:
                continue
            conflicts = int(occupied[bi][row_sets[j]].sum())
            if bundle_conflicts[bi] + conflicts <= budget:
                bundles[bi].append(int(j))
                occupied[bi][row_sets[j]] = True
                bundle_conflicts[bi] += conflicts
                bundle_bins[bi] += int(bins_per_feature[j])
                placed = True
                break
        if not placed:
            occ = np.zeros(n_rows, bool)
            occ[row_sets[j]] = True
            bundles.append([int(j)])
            occupied.append(occ)
            bundle_conflicts.append(0)
            bundle_bins.append(int(bins_per_feature[j]))
    return bundles


class SparseFeatureBundler(Estimator):
    """Learn an exclusive-feature bundling of a sparse feature column.

    inputCol: dense [N, F] array, scipy sparse matrix, or per-row sparse
    vectors. outputCol: dense [N, n_bundles] int32 category codes. The
    fitted model's `categorical_indexes()` lists every output column (pass
    to LightGBM* `categoricalSlotIndexes`).
    """

    inputCol = _p.Param("inputCol", "sparse feature column", "features")
    outputCol = _p.Param("outputCol", "bundled dense output column",
                         "bundled")
    maxConflictRate = _p.Param(
        "maxConflictRate",
        "max fraction of rows where bundled features may collide (EFB "
        "gamma); colliding rows keep the higher-count feature's code", 0.0,
        float)
    numValueBins = _p.Param(
        "numValueBins",
        "quantile bins per feature's nonzero values (1 = presence only, "
        "the right setting for hashed/one-hot input)", 1, int)
    maxBundleBins = _p.Param(
        "maxBundleBins",
        "bin budget per bundle incl. the shared zero bin (keep <= the "
        "trainer's maxBin)", 255, int)

    def _fit(self, df: DataFrame) -> "SparseFeatureBundlerModel":
        csc, n, f = _to_csc(df[self.get("inputCol")])
        csc.eliminate_zeros()
        k = max(int(self.get("numValueBins")), 1)
        nnz = np.diff(csc.indptr)
        # only populated columns get a row set (a 2^18 hash space is mostly
        # empty buckets — greedy_bundles skips nnz==0 anyway); CSC indices
        # within a column are already sorted, no per-column np.sort needed
        row_sets = {
            int(j): csc.indices[csc.indptr[j]:csc.indptr[j + 1]]
            for j in np.nonzero(nnz)[0]}
        bins_per = np.full(f, k, np.int64)
        bundles = greedy_bundles(row_sets, n, nnz,
                                 float(self.get("maxConflictRate")),
                                 bins_per, int(self.get("maxBundleBins")) - 1)
        # per-feature nonzero-value quantile edges (k > 1 only)
        edges = {}
        if k > 1:
            for b in bundles:
                for j in b:
                    vals = csc.data[csc.indptr[j]:csc.indptr[j + 1]]
                    qs = np.quantile(vals, np.linspace(0, 1, k + 1)[1:-1])
                    edges[j] = np.unique(qs)
        model = SparseFeatureBundlerModel(
            bundles=bundles, num_features=f, value_edges=edges,
            bins_per_feature=int(k))
        model.set("inputCol", self.get("inputCol"))
        model.set("outputCol", self.get("outputCol"))
        return model


class SparseFeatureBundlerModel(Model):
    inputCol = _p.Param("inputCol", "sparse feature column", "features")
    outputCol = _p.Param("outputCol", "bundled dense output column",
                         "bundled")
    bundleSpec = _p.Param("bundleSpec", "fitted bundling description", None,
                          complex=True)

    def __init__(self, bundles: Optional[List[List[int]]] = None,
                 num_features: int = 0, value_edges=None,
                 bins_per_feature: int = 1, **kw):
        super().__init__(**kw)
        if bundles is not None:
            self.set("bundleSpec", {
                "bundles": [list(map(int, b)) for b in bundles],
                "num_features": int(num_features),
                "bins_per_feature": int(bins_per_feature),
                "value_edges": {int(j): np.asarray(e).tolist()
                                for j, e in (value_edges or {}).items()},
            })

    @property
    def _spec(self):
        return self.get("bundleSpec")

    @property
    def num_bundles(self) -> int:
        return len(self._spec["bundles"])

    def categorical_indexes(self) -> List[int]:
        """Every output column is categorical — hand to the GBDT trainer."""
        return list(range(self.num_bundles))

    def transform(self, df: DataFrame) -> DataFrame:
        spec = self._spec
        csc, n, f = _to_csc(df[self.get("inputCol")])
        if f != spec["num_features"]:
            raise ValueError(
                f"bundler was fitted on {spec['num_features']} features, "
                f"input has {f}")
        k = spec["bins_per_feature"]

        def width(j):
            return (len(spec["value_edges"].get(j, [])
                        or spec["value_edges"].get(str(j), [])) + 1
                    if k > 1 else 1)

        out = np.zeros((n, len(spec["bundles"])), np.int32)
        if k == 1:
            # vectorized presence-only path: one COO sweep instead of a
            # Python loop over every original feature (65k-feature hashed
            # text: ~18 s -> <1 s)
            f_bundle = np.full(f, -1, np.int64)
            f_code = np.zeros(f, np.int64)
            f_rank = np.zeros(f, np.int64)  # position in bundle (nnz rank)
            for bi, bundle in enumerate(spec["bundles"]):
                idx = np.asarray(bundle, np.int64)
                f_bundle[idx] = bi
                f_code[idx] = 1 + np.arange(len(bundle))
                f_rank[idx] = np.arange(len(bundle))
            coo = csc.tocoo()
            keep = f_bundle[coo.col] >= 0
            r, c = coo.row[keep], coo.col[keep]
            # write lower-rank (higher-nnz) features LAST so they win the
            # (budgeted, rare) conflicts
            order = np.argsort(-f_rank[c], kind="stable")
            r, c = r[order], c[order]
            out[r, f_bundle[c]] = f_code[c].astype(np.int32)
            return df.with_column(self.get("outputCol"), out)
        for bi, bundle in enumerate(spec["bundles"]):
            # code layout: 0 = every feature zero; feature i of the bundle
            # owns the contiguous range [start_i, start_i + width_i)
            starts = np.cumsum([1] + [width(j) for j in bundle[:-1]])
            col = np.zeros(n, np.int32)
            # bundle order is descending nnz (EFB insertion order); write in
            # reverse so on (budgeted, rare) conflicts the higher-count
            # feature's code prevails
            for i in reversed(range(len(bundle))):
                j = bundle[i]
                rows = csc.indices[csc.indptr[j]:csc.indptr[j + 1]]
                vals = csc.data[csc.indptr[j]:csc.indptr[j + 1]]
                if k > 1:
                    e = np.asarray(spec["value_edges"].get(j, [])
                                   or spec["value_edges"].get(str(j), []))
                    code = starts[i] + np.searchsorted(e, vals, side="left")
                else:
                    code = np.full(rows.size, starts[i], np.int64)
                col[rows] = code.astype(np.int32)
            out[:, bi] = col
        return df.with_column(self.get("outputCol"), out)
