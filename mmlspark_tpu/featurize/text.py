"""Text featurization pipeline.

Reference: featurize/text/TextFeaturizer.scala:181-408 — tokenize -> stopword removal
-> n-grams -> hashingTF -> IDF, each stage toggleable; featurize/text/MultiNGram.scala
(concatenate several n-gram lengths); featurize/text/PageSplitter.scala (split long
strings into bounded pages).
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model, Transformer
from ..utils.hashing import hashing_tf

# Spark's default english stop words (StopWordsRemover) — abbreviated core set
_STOP_WORDS = set("""a about above after again against all am an and any are as at
be because been before being below between both but by can did do does doing down
during each few for from further had has have having he her here hers herself him
himself his how i if in into is it its itself just me more most my myself no nor
not now of off on once only or other our ours ourselves out over own same she
should so some such than that the their theirs them themselves then there these
they this those through to too under until up very was we were what when where
which while who whom why will with you your yours yourself yourselves""".split())


def tokenize(text: str) -> List[str]:
    return [t for t in re.split(r"\W+", text.lower()) if t]


def ngrams(tokens: Sequence[str], n: int) -> List[str]:
    if n <= 1:
        return list(tokens)
    return [" ".join(tokens[i:i + n]) for i in range(len(tokens) - n + 1)]


class TextFeaturizer(Estimator):
    """tokenize -> stopwords -> ngram -> hashingTF -> IDF as one estimator.

    Reference: featurize/text/TextFeaturizer.scala:181-408."""
    inputCol = _p.Param("inputCol", "text column", "input")
    outputCol = _p.Param("outputCol", "feature vector column", "output")
    useTokenizer = _p.Param("useTokenizer", "tokenize input", True, bool)
    useStopWordsRemover = _p.Param("useStopWordsRemover", "drop stop words", False, bool)
    useNGram = _p.Param("useNGram", "emit n-grams", False, bool)
    nGramLength = _p.Param("nGramLength", "n-gram length", 2, int)
    binary = _p.Param("binary", "binary term counts", False, bool)
    numFeatures = _p.Param("numFeatures", "hash space size", 1 << 18, int)
    useIDF = _p.Param("useIDF", "apply inverse document frequency", True, bool)
    minDocFreq = _p.Param("minDocFreq", "min doc frequency for IDF", 1, int)
    sparseOutput = _p.Param(
        "sparseOutput",
        "emit scipy CSR instead of a dense matrix (for wide hash spaces; "
        "pair with featurize.SparseFeatureBundler before dense consumers)",
        False, bool)

    def _tokens(self, col) -> List[List[str]]:
        docs = []
        for text in col:
            toks = tokenize(str(text)) if self.get("useTokenizer") else list(text)
            if self.get("useStopWordsRemover"):
                toks = [t for t in toks if t not in _STOP_WORDS]
            if self.get("useNGram"):
                toks = ngrams(toks, int(self.get("nGramLength")))
            docs.append(toks)
        return docs

    def _fit(self, df: DataFrame) -> "TextFeaturizerModel":
        docs = self._tokens(df[self.get("inputCol")])
        nf = int(self.get("numFeatures"))
        idf = None
        if self.get("useIDF"):
            # document frequencies via the sparse path: never materializes
            # the [N, 2^18] dense matrix during fit
            tf = hashing_tf(docs, nf, binary=True, sparse=True)
            dfreq = np.asarray(tf.sum(axis=0)).ravel()
            n_docs = len(docs)
            idf = np.log((n_docs + 1.0) / (dfreq + 1.0)).astype(np.float32)
            # terms below the doc-frequency threshold are filtered out (weight
            # 0), matching Spark IDF's minDocFreq semantics
            idf[dfreq < self.get("minDocFreq")] = 0.0
        model = TextFeaturizerModel(idf=idf)
        for p in ("inputCol", "outputCol", "useTokenizer", "useStopWordsRemover",
                  "useNGram", "nGramLength", "binary", "numFeatures",
                  "sparseOutput"):
            model.set(p, self.get(p))
        return model


class TextFeaturizerModel(Model):
    inputCol = _p.Param("inputCol", "text column", "input")
    outputCol = _p.Param("outputCol", "feature vector column", "output")
    useTokenizer = _p.Param("useTokenizer", "tokenize input", True, bool)
    useStopWordsRemover = _p.Param("useStopWordsRemover", "drop stop words", False, bool)
    useNGram = _p.Param("useNGram", "emit n-grams", False, bool)
    nGramLength = _p.Param("nGramLength", "n-gram length", 2, int)
    binary = _p.Param("binary", "binary term counts", False, bool)
    numFeatures = _p.Param("numFeatures", "hash space size", 1 << 18, int)
    sparseOutput = _p.Param(
        "sparseOutput",
        "emit scipy CSR instead of a dense matrix (for wide hash spaces)",
        False, bool)
    idf = _p.Param("idf", "idf weights (None = no idf)", None, complex=True)

    def __init__(self, idf: Optional[np.ndarray] = None, **kw):
        super().__init__(**kw)
        if idf is not None:
            self.set("idf", np.asarray(idf, np.float32))

    def transform(self, df: DataFrame) -> DataFrame:
        feat = TextFeaturizer()
        for p in ("inputCol", "useTokenizer", "useStopWordsRemover", "useNGram",
                  "nGramLength"):
            feat.set(p, self.get(p))
        docs = feat._tokens(df[self.get("inputCol")])
        sparse = bool(self.get("sparseOutput"))
        tf = hashing_tf(docs, int(self.get("numFeatures")),
                        binary=self.get("binary"), sparse=sparse)
        idf = self.get("idf") if self.is_set("idf") else None
        if idf is not None:
            if sparse:
                tf = tf.multiply(np.asarray(idf)[None, :]).tocsr()
                # minDocFreq-filtered terms get idf == 0; multiply keeps them
                # as STORED zeros, which downstream (the bundler) would count
                # as present — drop them so sparse == dense semantics
                tf.eliminate_zeros()
            else:
                tf = tf * idf[None, :]
        return df.with_column(self.get("outputCol"), tf)


class MultiNGram(Transformer):
    """Concatenate token n-grams for several lengths into one token column.

    Reference: featurize/text/MultiNGram.scala."""
    inputCol = _p.Param("inputCol", "token-list column", "input")
    outputCol = _p.Param("outputCol", "combined ngram column", "output")
    lengths = _p.Param("lengths", "ngram lengths to emit", None)

    def transform(self, df: DataFrame) -> DataFrame:
        lengths = [int(x) for x in (self.get("lengths") or [1, 2, 3])]
        col = df[self.get("inputCol")]
        out = np.empty(len(col), dtype=object)
        for i, toks in enumerate(col):
            toks = list(toks)
            combined: List[str] = []
            for n in lengths:
                combined.extend(ngrams(toks, n))
            out[i] = combined
        return df.with_column(self.get("outputCol"), out)


class PageSplitter(Transformer):
    """Split long strings into pages within [minPageLength, maxPageLength],
    preferring whitespace/boundary breaks.

    Reference: featurize/text/PageSplitter.scala."""
    inputCol = _p.Param("inputCol", "text column", "input")
    outputCol = _p.Param("outputCol", "list-of-pages column", "output")
    maxPageLength = _p.Param("maxPageLength", "max chars per page", 5000, int)
    minPageLength = _p.Param("minPageLength", "min chars before break", 4500, int)
    boundaryRegex = _p.Param("boundaryRegex", "preferred break pattern", r"\s")

    def transform(self, df: DataFrame) -> DataFrame:
        lo = int(self.get("minPageLength"))
        hi = int(self.get("maxPageLength"))
        pattern = re.compile(self.get("boundaryRegex"))
        col = df[self.get("inputCol")]
        out = np.empty(len(col), dtype=object)
        for i, text in enumerate(col):
            s = str(text)
            pages: List[str] = []
            while len(s) > hi:
                window = s[lo:hi]
                m = None
                for m in pattern.finditer(window):
                    pass  # last boundary in window
                cut = lo + m.end() if m else hi
                pages.append(s[:cut])
                s = s[cut:]
            if s:
                pages.append(s)
            out[i] = pages
        return df.with_column(self.get("outputCol"), out)
