"""Missing-data cleaning and column type coercion.

Reference: featurize/CleanMissingData.scala:49-160 (mean/median/custom replacement,
fitted per column), featurize/DataConversion.scala:21 (column type coercion).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model, Transformer


class CleanMissingData(Estimator):
    """Replace NaN/None in numeric columns by mean / median / custom value.

    Reference: featurize/CleanMissingData.scala:49-160."""
    inputCols = _p.Param("inputCols", "columns to clean", None)
    outputCols = _p.Param("outputCols", "cleaned output columns", None)
    cleaningMode = _p.Param("cleaningMode", "Mean | Median | Custom", "Mean")
    customValue = _p.Param("customValue", "replacement for Custom mode", None)

    def _fit(self, df: DataFrame) -> "CleanMissingDataModel":
        mode = self.get("cleaningMode")
        fills: List[float] = []
        for col_name in self.get("inputCols"):
            v = np.asarray(df[col_name], np.float64)
            finite = v[np.isfinite(v)]
            if mode == "Mean":
                fill = float(finite.mean()) if len(finite) else 0.0
            elif mode == "Median":
                fill = float(np.median(finite)) if len(finite) else 0.0
            elif mode == "Custom":
                fill = float(self.get("customValue"))
            else:
                raise ValueError(f"unknown cleaningMode {mode!r}")
            fills.append(fill)
        model = CleanMissingDataModel(fills=fills)
        model.set("inputCols", self.get("inputCols"))
        model.set("outputCols", self.get("outputCols") or self.get("inputCols"))
        return model


class CleanMissingDataModel(Model):
    inputCols = _p.Param("inputCols", "columns to clean", None)
    outputCols = _p.Param("outputCols", "cleaned output columns", None)
    fills = _p.Param("fills", "replacement value per column", None, complex=True)

    def __init__(self, fills: Optional[List[float]] = None, **kw):
        super().__init__(**kw)
        if fills is not None:
            self.set("fills", [float(f) for f in fills])

    def transform(self, df: DataFrame) -> DataFrame:
        out = df
        for col_name, out_name, fill in zip(self.get("inputCols"),
                                            self.get("outputCols"),
                                            self.get("fills")):
            v = np.asarray(df[col_name], np.float64).copy()
            v[~np.isfinite(v)] = fill
            out = out.with_column(out_name, v)
        return out


_DTYPES = {
    "boolean": np.bool_, "byte": np.int8, "short": np.int16, "integer": np.int32,
    "long": np.int64, "float": np.float32, "double": np.float64, "string": object,
}


class DataConversion(Transformer):
    """Coerce columns to a named type; `date` renders epoch-ms to strings.

    Reference: featurize/DataConversion.scala:21."""
    cols = _p.Param("cols", "columns to convert", None)
    convertTo = _p.Param("convertTo", "target type name", "double")
    dateTimeFormat = _p.Param("dateTimeFormat", "format for date conversion",
                              "yyyy-MM-dd HH:mm:ss")

    def transform(self, df: DataFrame) -> DataFrame:
        target = self.get("convertTo")
        out = df
        for name in self.get("cols") or []:
            col = df[name]
            if target == "string":
                conv = np.array([str(v) for v in col], dtype=object)
            elif target == "date":
                import datetime
                conv = np.array(
                    [datetime.datetime.fromtimestamp(float(v) / 1000.0)
                     .strftime("%Y-%m-%d %H:%M:%S") for v in col], dtype=object)
            elif target in _DTYPES:
                if col.dtype == object:
                    col = np.array([float(v) for v in col])
                conv = col.astype(_DTYPES[target])
            else:
                raise ValueError(f"unknown convertTo {target!r}")
            out = out.with_column(name, conv)
        return out
