"""KNN / ConditionalKNN pipeline stages.

Reference: nn/KNN.scala:45-115 (`KNN`/`KNNModel` — fit collects the feature
matrix + values payload, transform probes per row, emitting an array of
(value, distance) structs), nn/ConditionalKNN.scala:29-112 (adds per-query
`conditionerCol` allowed-label sets and a labelCol payload).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model
from .search import BallTree, ConditionalBallTree


class KNN(Estimator, _p.HasFeaturesCol, _p.HasOutputCol):
    valuesCol = _p.Param("valuesCol", "payload column returned with each "
                         "neighbor", "values")
    k = _p.Param("k", "number of neighbors", 5, int)
    leafSize = _p.Param("leafSize", "accepted for reference API parity; the "
                        "MXU brute-force search has no leaves", 50, int)

    def __init__(self, **kw):
        kw.setdefault("outputCol", "output")
        super().__init__(**kw)

    def _fit(self, df: DataFrame) -> "KNNModel":
        feats = np.asarray(df[self.get("featuresCol")], np.float32)
        model = KNNModel(points=feats,
                         values=df[self.get("valuesCol")].copy())
        for p in ("featuresCol", "outputCol", "k"):
            model.set(p, self.get(p))
        return model


class KNNModel(Model, _p.HasFeaturesCol, _p.HasOutputCol):
    k = _p.Param("k", "number of neighbors", 5, int)
    points = _p.Param("points", "index feature matrix", None, complex=True)
    values = _p.Param("values", "payload per index row", None, complex=True)

    def __init__(self, points: Optional[np.ndarray] = None, values=None, **kw):
        super().__init__(**kw)
        self._tree: Optional[BallTree] = None
        if points is not None:
            self._set(points=points, values=values)

    def _get_tree(self) -> BallTree:
        if self._tree is None:
            self._tree = BallTree(self.get("points"))
        return self._tree

    def transform(self, df: DataFrame) -> DataFrame:
        q = np.asarray(df[self.get("featuresCol")], np.float32)
        dist, idx = self._get_tree().query(q, self.get("k"))
        values = self.get("values")
        out = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            out[i] = [{"value": values[j], "distance": float(d)}
                      for j, d in zip(idx[i], dist[i])]
        return df.with_column(self.get("outputCol"), out)

    def _load_extra(self, path, extra):
        self._tree = None


class ConditionalKNN(Estimator, _p.HasFeaturesCol, _p.HasOutputCol,
                     _p.HasLabelCol):
    valuesCol = _p.Param("valuesCol", "payload column", "values")
    conditionerCol = _p.Param("conditionerCol",
                              "per-query iterable of allowed labels",
                              "conditioner")
    k = _p.Param("k", "number of neighbors", 5, int)
    leafSize = _p.Param("leafSize", "API parity; unused", 50, int)

    def __init__(self, **kw):
        kw.setdefault("outputCol", "output")
        super().__init__(**kw)

    def _fit(self, df: DataFrame) -> "ConditionalKNNModel":
        feats = np.asarray(df[self.get("featuresCol")], np.float32)
        model = ConditionalKNNModel(
            points=feats, values=df[self.get("valuesCol")].copy(),
            labels=df[self.get("labelCol")].copy())
        for p in ("featuresCol", "outputCol", "conditionerCol", "k"):
            model.set(p, self.get(p))
        return model


class ConditionalKNNModel(Model, _p.HasFeaturesCol, _p.HasOutputCol):
    conditionerCol = _p.Param("conditionerCol", "allowed-label column",
                              "conditioner")
    k = _p.Param("k", "number of neighbors", 5, int)
    points = _p.Param("points", "index feature matrix", None, complex=True)
    values = _p.Param("values", "payload per index row", None, complex=True)
    labels = _p.Param("labels", "label per index row", None, complex=True)

    def __init__(self, points: Optional[np.ndarray] = None, values=None,
                 labels=None, **kw):
        super().__init__(**kw)
        self._tree: Optional[ConditionalBallTree] = None
        if points is not None:
            self._set(points=points, values=values, labels=labels)

    def _get_tree(self) -> ConditionalBallTree:
        if self._tree is None:
            self._tree = ConditionalBallTree(self.get("points"),
                                             list(self.get("labels")))
        return self._tree

    def transform(self, df: DataFrame) -> DataFrame:
        q = np.asarray(df[self.get("featuresCol")], np.float32)
        conds = df[self.get("conditionerCol")]
        dist, idx = self._get_tree().query(q, self.get("k"), list(conds))
        values = self.get("values")
        labels = self.get("labels")
        out = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            out[i] = [{"value": values[j], "distance": float(d),
                       "label": labels[j]}
                      for j, d in zip(idx[i], dist[i]) if j >= 0]
        return df.with_column(self.get("outputCol"), out)

    def _load_extra(self, path, extra):
        self._tree = None
