"""nn/ — exact nearest-neighbor search (reference: nn/, 5 files, 598 LoC).
Ball trees are replaced by batched MXU distance contractions + lax.top_k."""

from .knn import KNN, ConditionalKNN, ConditionalKNNModel, KNNModel
from .search import BallTree, ConditionalBallTree

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel",
           "BallTree", "ConditionalBallTree"]
