"""Batched exact nearest-neighbor search — the BallTree, the TPU way.

Reference: nn/BallTree.scala:110 and nn/ConditionalBallTree.scala:203 build
serial ball trees per collected partition and probe them row-by-row with a
BoundedPriorityQueue (nn/KNN.scala:45-115). On TPU, exact brute-force search is
a matmul: ||q-x||^2 = ||q||^2 + ||x||^2 - 2 q.x — one [Q,D]x[D,N] contraction
on the MXU followed by `lax.top_k`, chunked over the index dimension to bound
HBM. This beats tree traversal (branchy, scalar) by orders of magnitude on this
hardware, and is exact, so results match the reference's trees.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("k",))
def _topk_chunk(q, x, x_sq, k: int):
    """Top-k smallest squared distances of queries q against index chunk x.
    Returns (neg_dist [Q,k], idx [Q,k])  (jax top_k takes largest => negate)."""
    d2 = (q * q).sum(1, keepdims=True) + x_sq[None, :] - 2.0 * (q @ x.T)
    return jax.lax.top_k(-d2, k)


@partial(jax.jit, static_argnames=("k",))
def _topk_chunk_masked(q, x, x_sq, allowed, k: int):
    """Same, with a per-(query, point) bool mask; disallowed -> +inf."""
    d2 = (q * q).sum(1, keepdims=True) + x_sq[None, :] - 2.0 * (q @ x.T)
    d2 = jnp.where(allowed, d2, jnp.inf)
    return jax.lax.top_k(-d2, k)


@partial(jax.jit, static_argnames=("k",))
def _merge_topk(neg_a, idx_a, neg_b, idx_b, k: int):
    """Merge two top-k candidate sets into one."""
    neg = jnp.concatenate([neg_a, neg_b], axis=1)
    idx = jnp.concatenate([idx_a, idx_b], axis=1)
    best_neg, pos = jax.lax.top_k(neg, k)
    return best_neg, jnp.take_along_axis(idx, pos, axis=1)


class BallTree:
    """Exact k-NN index (API parity with nn/BallTree.scala; brute-force MXU
    search inside). `chunk` bounds the index-side tile held in HBM."""

    def __init__(self, points: np.ndarray, chunk: int = 65536):
        self.points = np.ascontiguousarray(points, np.float32)
        self.chunk = int(chunk)
        self._sq = (self.points.astype(np.float64) ** 2).sum(1).astype(np.float32)

    def __len__(self) -> int:
        return len(self.points)

    def find_maximum_inner_products(self, queries: np.ndarray, k: int):
        """Reference-name alias (BallTree.findMaximumInnerProducts); here the
        metric is euclidean distance (matching KNN.scala usage)."""
        return self.query(queries, k)

    def query(self, queries: np.ndarray, k: int):
        """Returns (distances [Q,k], indices [Q,k]), ascending distance."""
        q = jnp.asarray(np.asarray(queries, np.float32))
        k = min(k, len(self.points))
        best = None
        for start in range(0, len(self.points), self.chunk):
            x = jnp.asarray(self.points[start:start + self.chunk])
            xs = jnp.asarray(self._sq[start:start + self.chunk])
            kk = min(k, x.shape[0])
            neg, idx = _topk_chunk(q, x, xs, kk)
            idx = idx + start
            if best is None:
                best = (neg, idx)
                if kk < k:  # first chunk smaller than k: pad with +inf
                    pad = k - kk
                    best = (jnp.pad(neg, ((0, 0), (0, pad)),
                                    constant_values=-jnp.inf),
                            jnp.pad(idx, ((0, 0), (0, pad))))
            else:
                if kk < k:
                    neg = jnp.pad(neg, ((0, 0), (0, k - kk)),
                                  constant_values=-jnp.inf)
                    idx = jnp.pad(idx, ((0, 0), (0, k - kk)))
                best = _merge_topk(best[0], best[1], neg, idx, k)
        neg, idx = best
        d2 = np.maximum(-np.asarray(neg), 0.0)
        return np.sqrt(d2), np.asarray(idx)


class ConditionalBallTree:
    """k-NN with a per-query allowed-label set (nn/ConditionalBallTree.scala:203;
    python binding nn/ConditionalBallTree.py). Masking replaces tree pruning."""

    def __init__(self, points: np.ndarray, labels: Sequence,
                 chunk: int = 65536):
        self.tree = BallTree(points, chunk)
        self.labels = list(labels)
        self._levels = sorted(set(self.labels), key=str)
        self._level_idx = {v: i for i, v in enumerate(self._levels)}
        self._label_codes = np.array([self._level_idx[v] for v in self.labels],
                                     np.int32)

    def __len__(self) -> int:
        return len(self.tree)

    def query(self, queries: np.ndarray, k: int, conditioners: Sequence):
        """conditioners: per-query iterable of allowed label values.
        Returns (distances, indices); slots with no allowed neighbor left get
        distance inf / index -1."""
        q = np.asarray(queries, np.float32)
        n_levels = len(self._levels)
        allow_mat = np.zeros((len(q), n_levels), bool)
        for i, cond in enumerate(conditioners):
            for v in cond:
                j = self._level_idx.get(v)
                if j is not None:
                    allow_mat[i, j] = True
        k = min(k, len(self.tree))
        qj = jnp.asarray(q)
        best = None
        pts, sq = self.tree.points, self.tree._sq
        chunk = self.tree.chunk
        for start in range(0, len(pts), chunk):
            x = jnp.asarray(pts[start:start + chunk])
            xs = jnp.asarray(sq[start:start + chunk])
            codes = self._label_codes[start:start + chunk]
            allowed = jnp.asarray(allow_mat[:, codes])
            kk = min(k, x.shape[0])
            neg, idx = _topk_chunk_masked(qj, x, xs, allowed, kk)
            idx = idx + start
            if kk < k:
                neg = jnp.pad(neg, ((0, 0), (0, k - kk)),
                              constant_values=-jnp.inf)
                idx = jnp.pad(idx, ((0, 0), (0, k - kk)))
            best = ((neg, idx) if best is None
                    else _merge_topk(best[0], best[1], neg, idx, k))
        neg, idx = np.asarray(best[0]), np.asarray(best[1])
        dead = ~np.isfinite(neg)
        d2 = np.maximum(-neg, 0.0)
        d = np.sqrt(np.where(dead, np.inf, d2))
        idx = np.where(dead, -1, idx)
        return d, idx
