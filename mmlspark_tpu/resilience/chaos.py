"""Deterministic, seed-driven fault injection for chaos testing.

Wraps any callable/transport to inject error/delay/drop faults by
probability, with the whole fault schedule derived from one seed — same
seed => same fault sequence, so a chaos run that loses a request replays
exactly. Used by tests/test_resilience.py to prove the serving stack
completes N requests with zero losses while workers are killed and a
configured fraction of gateway forwards fail.

Fault kinds:
- error: raise `InjectedFault` (a ConnectionError) BEFORE invoking the
  wrapped callable — models an unreachable peer; the call never happens,
  so retries cannot duplicate work.
- delay: sleep `delay_s`, then invoke normally — models a straggler hop
  ("Understanding and Optimizing Distributed ML on Spark", arxiv
  1612.01437: straggler behavior dominates tail latency).
- drop: raise `InjectedDrop` (a TimeoutError) before invoking — models a
  request lost in flight with no response ever coming back.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List


class InjectedFault(ConnectionError):
    """A chaos-injected transport error (peer unreachable)."""


class InjectedDrop(TimeoutError):
    """A chaos-injected silent drop (no reply ever arrives)."""


class FaultInjector:
    """Seeded fault source; `wrap(fn)` returns fn with faults injected.

    Rates are independent probabilities evaluated in order
    error -> drop -> delay from ONE uniform draw per call, so the decision
    sequence is a pure function of (seed, rates) — `schedule(n)` previews
    it without consuming state.
    """

    def __init__(self, seed: int = 0, error_rate: float = 0.0,
                 drop_rate: float = 0.0, delay_rate: float = 0.0,
                 delay_s: float = 0.05):
        if min(error_rate, drop_rate, delay_rate) < 0 or \
                error_rate + drop_rate + delay_rate > 1.0:
            raise ValueError("fault rates must be >= 0 and sum to <= 1")
        self.seed = seed
        self.error_rate = error_rate
        self.drop_rate = drop_rate
        self.delay_rate = delay_rate
        self.delay_s = delay_s
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {"calls": 0, "error": 0, "drop": 0,
                                       "delay": 0, "ok": 0}

    def _classify(self, u: float) -> str:
        if u < self.error_rate:
            return "error"
        if u < self.error_rate + self.drop_rate:
            return "drop"
        if u < self.error_rate + self.drop_rate + self.delay_rate:
            return "delay"
        return "ok"

    def next_fault(self) -> str:
        """Draw the next fault decision (thread-safe). Each decision is
        also counted into the telemetry registry
        (`chaos_injected_total{kind=...}`); `self.counts` stays an
        INDEPENDENT tally so chaos tests can reconcile registry counters
        against ground truth that does not share the registry's code
        path."""
        with self._lock:
            u = self._rng.random()
            kind = self._classify(u)
            self.counts["calls"] += 1
            self.counts[kind] += 1
        try:
            from ..observability import get_registry
            get_registry().counter(
                "chaos_injected_total", "chaos decisions by kind",
                labels={"kind": kind}).inc()
        except Exception:  # noqa: BLE001 - telemetry must not alter chaos
            pass
        return kind

    def schedule(self, n: int) -> List[str]:
        """The first n decisions a fresh injector with this seed makes —
        the determinism contract (same seed => same fault schedule). Does
        not consume this injector's state."""
        rng = random.Random(self.seed)
        return [self._classify(rng.random()) for _ in range(n)]

    def wrap(self, fn: Callable) -> Callable:
        def chaotic(*args, **kw):
            kind = self.next_fault()
            if kind == "error":
                raise InjectedFault("injected fault: peer unreachable")
            if kind == "drop":
                raise InjectedDrop("injected drop: no reply")
            if kind == "delay":
                time.sleep(self.delay_s)
            return fn(*args, **kw)
        return chaotic
