"""Deterministic, seed-driven fault injection for chaos testing.

Wraps any callable/transport to inject error/delay/drop faults by
probability, with the whole fault schedule derived from one seed — same
seed => same fault sequence, so a chaos run that loses a request replays
exactly. Used by tests/test_resilience.py to prove the serving stack
completes N requests with zero losses while workers are killed and a
configured fraction of gateway forwards fail.

Fault kinds:
- error: raise `InjectedFault` (a ConnectionError) BEFORE invoking the
  wrapped callable — models an unreachable peer; the call never happens,
  so retries cannot duplicate work.
- delay: sleep `delay_s`, then invoke normally — models a straggler hop
  ("Understanding and Optimizing Distributed ML on Spark", arxiv
  1612.01437: straggler behavior dominates tail latency).
- drop: raise `InjectedDrop` (a TimeoutError) before invoking — models a
  request lost in flight with no response ever coming back.

`TrainingFaultInjector` extends the suite from transport faults to
TRAINING faults (ISSUE 10): a seeded kill at a chunk boundary (the GBDT
chunk loop's `_chunk_boundary_hook`, fired after that chunk's snapshot
lands — exactly a pool preemption's timing), a seeded device-loss
downshift (resume at fewer devices than the killed fit), and snapshot
corruption (truncation / bit flips / tmp litter) against which
`resilience.elastic.CheckpointStore`'s digest fallback is proved.

Round 13 adds SWAP faults against the model lifecycle (io/registry.py +
ServingServer.hot_swap): `corrupt_version_payload` damages a published
model version's artifact bytes (the registry digest gate must fail the
swap LOAD and the worker must keep serving the old version), and
`slow_load` wraps a swap loader with a delay (the slow-load canary — the
coordinator's rollout timeout must roll the fleet back while the old
version keeps serving throughout).

Round 19 adds REWARD-PLANE faults (ISSUE 19) for the train-on-traffic
loop: `RewardFaultInjector` mutates the reward event stream itself —
duplicate_reward (at-least-once transport re-delivery), delay_reward
(the reward arrives beyond the join horizon), drop_reward (the reward
never arrives). Counts are independent ground truth; the loop chaos
tests reconcile them EXACTLY against the RewardJoiner's refusal/eviction
tallies (duplicates == `duplicate` refusals, delays == `expired`
refusals, drops == `reward_timeout` evictions).
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from typing import Callable, Dict, List, Optional


def derive_seed(master_seed: int, injector_name: str) -> int:
    """One scenario seed -> every sub-injector's seed (ISSUE 20).

    `hash(seed, injector_name)` via sha256 — NOT Python's builtin
    `hash()`, which is salted per process and would break the replay
    contract across runs. The derivation is a pure function of its two
    arguments, so a multi-injector chaos run (transport + training +
    reward planes at once) replays from a single number: same master
    seed => every sub-injector draws the identical fault schedule
    (docs/RESILIENCE.md, "Determinism contract")."""
    h = hashlib.sha256(
        f"{int(master_seed)}:{injector_name}".encode()).digest()
    return int.from_bytes(h[:8], "big")


class InjectedFault(ConnectionError):
    """A chaos-injected transport error (peer unreachable)."""


class InjectedDrop(TimeoutError):
    """A chaos-injected silent drop (no reply ever arrives)."""


class InjectedKill(RuntimeError):
    """A chaos-injected process death (pool preemption / OOM-kill): the
    fit dies at a chunk boundary, after that chunk's snapshot landed."""


class FaultInjector:
    """Seeded fault source; `wrap(fn)` returns fn with faults injected.

    Rates are independent probabilities evaluated in order
    error -> drop -> delay from ONE uniform draw per call, so the decision
    sequence is a pure function of (seed, rates) — `schedule(n)` previews
    it without consuming state.
    """

    def __init__(self, seed: int = 0, error_rate: float = 0.0,
                 drop_rate: float = 0.0, delay_rate: float = 0.0,
                 delay_s: float = 0.05, event_log=None):
        if min(error_rate, drop_rate, delay_rate) < 0 or \
                error_rate + drop_rate + delay_rate > 1.0:
            raise ValueError("fault rates must be >= 0 and sum to <= 1")
        self.seed = seed
        self.error_rate = error_rate
        self.drop_rate = drop_rate
        self.delay_rate = delay_rate
        self.delay_s = delay_s
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {"calls": 0, "error": 0, "drop": 0,
                                       "delay": 0, "ok": 0}
        #: optional system-event bridge (ISSUE 14): injected faults (not
        #: "ok" draws) land as `chaos` events on this EventLog — pass the
        #: gateway's log so the fleet trace collector sees the injections
        #: beside the forward failures they caused (incident bundles)
        self.event_log = event_log

    @classmethod
    def from_master(cls, master_seed: int, injector_name: str,
                    **kw) -> "FaultInjector":
        """Sub-injector keyed off one scenario master seed: the seed is
        `derive_seed(master_seed, injector_name)` — the multi-injector
        replay contract (same master seed => same schedule per name)."""
        inj = cls(seed=derive_seed(master_seed, injector_name), **kw)
        inj.injector_name = injector_name
        return inj

    def _classify(self, u: float) -> str:
        if u < self.error_rate:
            return "error"
        if u < self.error_rate + self.drop_rate:
            return "drop"
        if u < self.error_rate + self.drop_rate + self.delay_rate:
            return "delay"
        return "ok"

    def next_fault(self) -> str:
        """Draw the next fault decision (thread-safe). Each decision is
        also counted into the telemetry registry
        (`chaos_injected_total{kind=...}`); `self.counts` stays an
        INDEPENDENT tally so chaos tests can reconcile registry counters
        against ground truth that does not share the registry's code
        path."""
        with self._lock:
            u = self._rng.random()
            kind = self._classify(u)
            self.counts["calls"] += 1
            self.counts[kind] += 1
        try:
            from ..observability import get_registry
            get_registry().counter(
                "chaos_injected_total", "chaos decisions by kind",
                labels={"kind": kind}).inc()
        except Exception:  # noqa: BLE001 - telemetry must not alter chaos
            pass
        if kind != "ok" and self.event_log is not None:
            try:
                self.event_log.append("chaos", kind=kind, seed=self.seed)
            except Exception:  # noqa: BLE001 - tracing must not alter chaos
                pass
        return kind

    def schedule(self, n: int) -> List[str]:
        """The first n decisions a fresh injector with this seed makes —
        the determinism contract (same seed => same fault schedule). Does
        not consume this injector's state."""
        rng = random.Random(self.seed)
        return [self._classify(rng.random()) for _ in range(n)]

    def wrap(self, fn: Callable) -> Callable:
        def chaotic(*args, **kw):
            kind = self.next_fault()
            if kind == "error":
                raise InjectedFault("injected fault: peer unreachable")
            if kind == "drop":
                raise InjectedDrop("injected drop: no reply")
            if kind == "delay":
                time.sleep(self.delay_s)
            return fn(*args, **kw)
        return chaotic


class TrainingFaultInjector:
    """Seeded fit-level faults: kill-at-chunk-boundary + ndev downshift.

    ``arm(estimator)`` installs ``chunk_boundary`` as the estimator's
    `_chunk_boundary_hook`; the GBDT chunk loop calls it (inside the
    designated host-sync point, AFTER the chunk's snapshot write) with
    the chunk's starting iteration. The kill boundary comes from the seed
    unless pinned, so a chaos run replays exactly — the same determinism
    contract as `FaultInjector.schedule`.

    ``self.counts`` stays an INDEPENDENT tally (boundaries seen, kills
    fired) so tests can reconcile registry counters against ground truth
    that does not share the registry's code path.

    ``kill_host`` (ISSUE 15) turns the kill into a HOST fault on a
    multi-process mesh: armed identically on every host (same seed, same
    boundary — SPMD discipline), it fires only on the process whose
    `jax.process_index()` matches, modelling exactly one host of the
    fleet dying mid-fit. The surviving hosts' next collective wedges;
    the fabric's heartbeat reaper (parallel/multihost.py) hard-exits
    them, and recovery is PR 10's elastic resume at the surviving device
    count from the last durable snapshot — proved digest-identical in
    tests/test_multihost_fabric.py.
    """

    def __init__(self, seed: int = 0, kill_at_chunk: Optional[int] = None,
                 max_chunk: int = 4, kill_host: Optional[int] = None,
                 process_index_fn: Optional[Callable[[], int]] = None):
        self.seed = seed
        self._rng = random.Random(seed)
        self.kill_at_chunk = (self._rng.randrange(max_chunk)
                              if kill_at_chunk is None else int(kill_at_chunk))
        #: None = kill wherever armed; int = only the host (jax process)
        #: with that index dies — the others count a 'spared' boundary
        self.kill_host = kill_host
        self._process_index_fn = process_index_fn
        # 'spared' appears only for host faults: plain train-kill tests
        # reconcile this dict EXACTLY against {boundaries, kills}
        self.counts: Dict[str, int] = {"boundaries": 0, "kills": 0}
        if kill_host is not None:
            self.counts["spared"] = 0

    @classmethod
    def from_master(cls, master_seed: int, injector_name: str,
                    **kw) -> "TrainingFaultInjector":
        """Sub-injector keyed off one scenario master seed (same
        derivation as `FaultInjector.from_master`): with no pinned
        `kill_at_chunk` the kill boundary is drawn from the DERIVED seed,
        so the whole training-fault plan replays from the master."""
        inj = cls(seed=derive_seed(master_seed, injector_name), **kw)
        inj.injector_name = injector_name
        return inj

    def _process_index(self) -> int:
        if self._process_index_fn is not None:
            return int(self._process_index_fn())
        try:
            import jax
            return int(jax.process_index())
        except Exception:  # noqa: BLE001 - no jax/distributed = host 0
            return 0

    def chunk_boundary(self, chunk_index: int, start_iter: int) -> None:
        """The fit loop's per-chunk callback; raises `InjectedKill` at the
        scheduled boundary. The kill ordinal counts boundaries GLOBALLY
        across an estimator's whole fit (numBatches>1 restarts
        `chunk_index` per batch — a global ordinal can kill mid-batch-1,
        a per-batch one never could)."""
        idx = self.counts["boundaries"]
        self.counts["boundaries"] += 1
        if idx != self.kill_at_chunk:
            return
        if self.kill_host is not None \
                and self._process_index() != self.kill_host:
            # this host survives its peer's death — the wedge + reap is
            # the fabric's job, not the injector's
            self.counts["spared"] += 1
            return
        self.counts["kills"] += 1
        try:
            from ..observability import get_registry
            get_registry().counter(
                "chaos_injected_total", "chaos decisions by kind",
                labels={"kind": "train_kill"}).inc()
        except Exception:  # noqa: BLE001 - telemetry must not alter chaos
            pass
        raise InjectedKill(
            f"injected kill at chunk boundary {chunk_index} "
            f"(iteration {start_iter}: snapshot already durable"
            + (f"; host {self.kill_host} of the mesh dies"
               if self.kill_host is not None else "") + ")")

    def arm(self, estimator):
        """Install on a LightGBM-style estimator; returns it for chaining."""
        estimator._chunk_boundary_hook = self.chunk_boundary
        return estimator

    def downshift_ndev(self, ndev: int) -> int:
        """Seeded device-loss model: a resume-time device count drawn
        (seeded) from the proper divisors of ``ndev`` — the shrunken mesh
        must still evenly tile the original shard layout's row space."""
        divisors = [d for d in range(1, ndev) if ndev % d == 0]
        if not divisors:
            raise ValueError(f"cannot downshift from ndev={ndev}")
        return self._rng.choice(divisors)

    @staticmethod
    def corrupt_version_payload(model_registry, version: int,
                                mode: str = "flip") -> str:
        """Damage one payload file of a published MODEL version (the
        corrupt-artifact swap fault, mirror of the snapshot corruption
        above): ``flip`` xors one byte mid-file (bit rot), ``truncate``
        halves it (torn publish the atomic writer makes impossible, but a
        disk can still produce). The registry's per-file sha256 gate must
        turn the next swap of this version into a counted rollback_load —
        never a crash, never a silently-wrong model. Returns the path of
        the damaged file."""
        import os
        man = model_registry.manifest(version)
        if not man or not man.get("files"):
            raise ValueError(f"version {version} has no payload to corrupt")
        rel = sorted(man["files"])[0]
        path = os.path.join(model_registry.version_dir(version), rel)
        with open(path, "r+b") as fh:
            data = fh.read()
            fh.seek(0)
            if mode == "flip":
                mid = len(data) // 2
                fh.write(data[:mid] + bytes([data[mid] ^ 0xFF])
                         + data[mid + 1:])
            elif mode == "truncate":
                fh.truncate(0)
                fh.write(data[:max(1, len(data) // 2)])
            else:
                raise ValueError(f"unknown corruption mode {mode!r}")
        return path

    @staticmethod
    def slow_load(load_fn: Callable, delay_s: float) -> Callable:
        """Wrap a swap loader with a straggler delay (the slow-load canary
        fault): the old handler must keep serving for the whole delay and
        the coordinator's rollout timeout must fire if the delay outlasts
        it."""
        def slow():
            time.sleep(delay_s)
            return load_fn()
        return slow

    @staticmethod
    def corrupt_latest_snapshot(store, mode: str = "truncate") -> int:
        """Damage the newest committed snapshot's payload — the
        crash-during/after-write fault the digest check exists to catch.
        ``truncate`` halves the file (torn write); ``flip`` xors one byte
        (bit rot); ``tmp_litter`` only drops an interrupted temp file
        beside the snapshots (must be IGNORED by restore, not a fault).
        Returns the affected sequence number."""
        seqs = store.snapshot_seqs()
        if not seqs:
            raise ValueError("store holds no snapshot to corrupt")
        seq = seqs[-1]
        ppath, _ = store._paths(seq)
        if mode == "tmp_litter":
            import os
            with open(os.path.join(store.directory,
                                   ".snapshot_corrupt.txt.tmp"), "w") as fh:
                fh.write("torn")
            return seq
        with open(ppath, "r+b") as fh:
            data = fh.read()
            fh.seek(0)
            if mode == "truncate":
                fh.truncate(0)
                fh.write(data[:max(1, len(data) // 2)])
            elif mode == "flip":
                mid = len(data) // 2
                fh.write(data[:mid] + bytes([data[mid] ^ 0xFF])
                         + data[mid + 1:])
            else:
                raise ValueError(f"unknown corruption mode {mode!r}")
        return seq


class RewardFaultInjector:
    """Seeded reward-STREAM faults for the train-on-traffic loop.

    Where `FaultInjector` breaks transports and `TrainingFaultInjector`
    breaks fits, this one breaks the reward events themselves — the
    faults a delayed-feedback pipeline actually delivers. `mutate(event)`
    passes predictions through untouched and maps each reward event to a
    LIST of events:

    - duplicate_reward: the event is emitted twice back to back — the
      at-least-once re-delivery the joiner's seen-ring must refuse.
    - delay_reward: the event's timestamp is pushed `delay_beyond_s`
      PAST the join horizon (and behind its prediction), so the joiner
      must refuse it as `expired` — never apply it, never crash.
    - drop_reward: the event is removed; the joiner must eventually
      evict the matching prediction as `reward_timeout`.

    One uniform draw per reward event classifies duplicate -> delay ->
    drop, so the schedule is a pure function of (seed, rates) —
    `schedule(n)` previews it without consuming state, the same
    determinism contract as `FaultInjector`. `self.counts` is the
    independent ground truth the chaos tests reconcile exactly against
    the joiner's refusal counters.
    """

    def __init__(self, seed: int = 0, duplicate_rate: float = 0.0,
                 delay_rate: float = 0.0, drop_rate: float = 0.0,
                 horizon_s: float = 300.0, delay_beyond_s: float = 1.0):
        if min(duplicate_rate, delay_rate, drop_rate) < 0 or \
                duplicate_rate + delay_rate + drop_rate > 1.0:
            raise ValueError("fault rates must be >= 0 and sum to <= 1")
        self.seed = seed
        self.duplicate_rate = duplicate_rate
        self.delay_rate = delay_rate
        self.drop_rate = drop_rate
        self.horizon_s = float(horizon_s)
        self.delay_beyond_s = float(delay_beyond_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {
            "rewards": 0, "duplicate_reward": 0, "delay_reward": 0,
            "drop_reward": 0, "ok": 0}

    @classmethod
    def from_master(cls, master_seed: int, injector_name: str,
                    **kw) -> "RewardFaultInjector":
        """Sub-injector keyed off one scenario master seed (same
        derivation as `FaultInjector.from_master`)."""
        inj = cls(seed=derive_seed(master_seed, injector_name), **kw)
        inj.injector_name = injector_name
        return inj

    def _classify(self, u: float) -> str:
        if u < self.duplicate_rate:
            return "duplicate_reward"
        if u < self.duplicate_rate + self.delay_rate:
            return "delay_reward"
        if u < self.duplicate_rate + self.delay_rate + self.drop_rate:
            return "drop_reward"
        return "ok"

    def schedule(self, n: int) -> List[str]:
        """First n decisions a fresh injector with this seed makes (the
        determinism contract); does not consume this injector's state."""
        rng = random.Random(self.seed)
        return [self._classify(rng.random()) for _ in range(n)]

    def mutate(self, event: Dict) -> List[Dict]:
        """Apply the next seeded fault decision to one event. Predictions
        and non-events pass through unchanged (the fault plane is the
        REWARD stream); each reward costs exactly one draw."""
        if event.get("kind") != "reward":
            return [event]
        with self._lock:
            u = self._rng.random()
            kind = self._classify(u)
            self.counts["rewards"] += 1
            self.counts[kind] += 1
        if kind != "ok":
            try:
                from ..observability import get_registry
                get_registry().counter(
                    "chaos_injected_total", "chaos decisions by kind",
                    labels={"kind": kind}).inc()
            except Exception:  # noqa: BLE001 - telemetry must not alter chaos
                pass
        if kind == "duplicate_reward":
            return [event, dict(event)]
        if kind == "delay_reward":
            late = dict(event)
            # beyond the horizon measured from the reward's own ts — the
            # prediction's ts is never later, so the join must expire
            late["ts"] = float(event["ts"]) + self.horizon_s \
                + self.delay_beyond_s
            return [late]
        if kind == "drop_reward":
            return []
        return [event]

    def mutate_stream(self, events) -> List[Dict]:
        out: List[Dict] = []
        for ev in events:
            out.extend(self.mutate(ev))
        return out
