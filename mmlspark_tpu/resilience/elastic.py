"""Elastic-recovery layer for training: durable checkpoints + preemption drain.

On shared TPU pools preemption is the normal case, not the exception —
failure/straggler recovery structure, not steady-state compute, dominates
distributed ML wall-clock (arxiv 1612.01437) — and PR 9's mesh-default fit
means one preempted chip now loses an entire 8-shard fit. The reference
inherited Spark's task-retry lineage story (PAPER.md §0); this module is
the TPU-native replacement, built around three primitives:

- ``atomic_write_bytes``/``atomic_write_text`` — THE one write-to-temp +
  fsync + rename helper. Every checkpoint byte in the codebase goes
  through it (tests/test_elastic.py lints that no checkpoint-owning
  module opens a file for writing or calls os.replace anywhere else), so
  a crash can truncate only a temp file, never a committed snapshot.
- ``CheckpointStore`` — numbered snapshots, each a payload file plus a
  JSON manifest (schema version, sha256 content digest, step, ndev,
  batch index). The manifest is written AFTER its payload: a snapshot
  without a valid manifest is in-progress garbage, not state. Restore
  walks newest-first, verifies the digest, and falls back to the
  previous snapshot on a corrupt/truncated file instead of crashing —
  keep-last-K retention guarantees there is a previous one. Save /
  restore / fallback events land in the PR 8 metrics registry.
- ``PreemptionDrain`` — a SIGTERM/SIGINT handler installed for the
  duration of fit(): the first signal requests a drain (finish the
  in-flight chunk, write the snapshot, raise ``Preempted``) and arms a
  grace-budget watchdog that hard-exits if the drain cannot complete in
  time; a second signal interrupts immediately. Wired into the GBDT
  chunk loop (models/lightgbm/base.py) and honored by
  scripts/tpu_recovery_watch.sh, which forwards TERM to its children.

The elastic-resume CONTRACT this enables (docs/RESILIENCE.md): booster
state is replicated, row data is not — a snapshot written at ndev=N
restores at ndev=M because resume re-bins and re-shards rows through
`parallel/mesh.shard_rows` at the CURRENT device count, and PR 9's
sharded==serial digest gate makes the result provably identical to an
uninterrupted serial fit.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import signal
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "SCHEMA_VERSION", "Preempted", "atomic_write_bytes", "atomic_write_text",
    "CheckpointStore", "PreemptionDrain", "publish_event",
]

#: manifest schema. v1: digest/payload/step/ndev/batch_index/extra.
#: v2 (out-of-core data plane): + optional ``shard_cursor`` — the shard
#: store identity (path/manifest_digest/shards/rows) the snapshot was
#: trained against, so a resume can refuse a rewritten store. v1
#: manifests restore fine (the cursor defaults to absent — a counted
#: ``legacy_schema`` restore, not a failure). Bump again on any field
#: whose ABSENCE a reader cannot default (dart resume would be v3: it
#: additionally needs the per-iteration dropout delta history — device
#: training state the booster payload does not carry).
SCHEMA_VERSION = 2

_SNAP_RE = re.compile(r"^snapshot_(\d{8})\.json$")


class Preempted(RuntimeError):
    """A fit drained cleanly after SIGTERM/SIGINT: the in-flight chunk was
    finished and snapshotted. Re-running fit() with the same checkpointDir
    resumes from that snapshot (at any device count)."""


def publish_event(event: str, outcome: str = "ok",
                  seconds: Optional[float] = None) -> None:
    """Checkpoint/drain telemetry — guarded: the elastic layer (and every
    resume/GC site that reports through it) must keep working with the
    observability layer broken or mid-shutdown. The ONE guarded wrapper:
    callers never hand-roll the try/import/except-pass pattern."""
    try:
        from ..observability import publish_checkpoint_event
        publish_checkpoint_event(event, outcome=outcome, seconds=seconds)
    except Exception:  # noqa: BLE001 - telemetry never fails recovery
        pass


_publish = publish_event  # internal alias


# ------------------------------------------------------------ atomic write

def atomic_write_bytes(path: str, data: bytes) -> None:
    """THE durable-write primitive: temp file in the destination directory
    -> flush -> fsync -> rename over the target -> fsync the directory.
    A crash at any point leaves either the old committed file or a stray
    ``.tmp`` — never a truncated target (the fsync-before-rename ordering
    is what makes the rename a commit point on a journaled fs)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d,
                               prefix="." + os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename still atomic


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def _digest(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


# --------------------------------------------------------- checkpoint store

class CheckpointStore:
    """Durable, integrity-checked, keep-last-K snapshot directory.

    Layout: ``snapshot_NNNNNNNN.txt`` (payload) + ``snapshot_NNNNNNNN.json``
    (manifest) per snapshot, NNNNNNNN a monotonically increasing sequence.
    The manifest commits a snapshot (written after the payload, both via
    the atomic helper): restore treats payload-without-manifest as an
    interrupted save and skips it silently; manifest-with-bad-payload is a
    FALLBACK event (counted, warned) and restore returns the previous
    snapshot. ``keep_last`` >= 2 so there always IS a previous snapshot to
    fall back to.
    """

    def __init__(self, directory: str, keep_last: int = 2):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.directory = os.path.abspath(directory)
        self.keep_last = int(keep_last)

    # ------------------------------------------------------------- listing
    def snapshot_seqs(self) -> List[int]:
        """Committed (manifest-bearing) snapshot sequence numbers, oldest
        first. In-progress payloads and stray tmp litter are invisible."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        return sorted(int(m.group(1)) for n in names
                      if (m := _SNAP_RE.match(n)))

    def _paths(self, seq: int) -> Tuple[str, str]:
        base = os.path.join(self.directory, f"snapshot_{seq:08d}")
        return base + ".txt", base + ".json"

    # ---------------------------------------------------------------- save
    def save(self, payload: str, *, step: int, ndev: int,
             batch_index: int = 0,
             extra: Optional[Dict[str, Any]] = None,
             shard_cursor: Optional[Dict[str, Any]] = None
             ) -> Dict[str, Any]:
        """Write one snapshot (payload then manifest, both atomic), then
        apply keep-last-K retention. Returns the manifest dict.
        ``shard_cursor`` (schema v2, out-of-core fits) records the shard
        store identity the snapshot trained on (ShardStore.cursor())."""
        t0 = time.perf_counter()
        data = payload.encode("utf-8")
        seqs = self.snapshot_seqs()
        seq = (seqs[-1] + 1) if seqs else 0
        ppath, mpath = self._paths(seq)
        manifest = {
            "schema_version": SCHEMA_VERSION,
            "payload": os.path.basename(ppath),
            "digest": _digest(data),
            "bytes": len(data),
            "step": int(step),
            "ndev": int(ndev),
            "batch_index": int(batch_index),
            "extra": dict(extra or {}),
        }
        if shard_cursor is not None:
            manifest["shard_cursor"] = dict(shard_cursor)
        try:
            atomic_write_bytes(ppath, data)
            atomic_write_text(mpath, json.dumps(manifest, sort_keys=True))
        except BaseException:
            _publish("save", outcome="error")
            raise
        self._gc(keep=self.keep_last)
        _publish("save", seconds=time.perf_counter() - t0)
        return manifest

    def _gc(self, keep: int) -> None:
        for seq in self.snapshot_seqs()[:-keep] if keep else []:
            self._remove(seq)

    def _remove(self, seq: int) -> None:
        for p in self._paths(seq):
            try:
                os.remove(p)
            except OSError:
                # a read-only/permission-lost dir (common post-crash state)
                # must not break restore's never-crash contract: the corpse
                # stays, the fallback still returns the valid snapshot
                pass

    # ------------------------------------------------------------- restore
    def restore(self) -> Optional[Tuple[str, Dict[str, Any]]]:
        """Newest digest-valid snapshot as ``(payload, manifest)``, or None
        when the store holds none. A corrupt/truncated newest snapshot is
        a counted FALLBACK to the one before it — never a crash, and never
        a silent train-from-scratch (the caller sees None only when no
        committed snapshot verifies)."""
        t0 = time.perf_counter()
        seqs = self.snapshot_seqs()
        for seq in reversed(seqs):
            ppath, mpath = self._paths(seq)
            reason = None
            try:
                with open(mpath, encoding="utf-8") as fh:
                    manifest = json.load(fh)
            except (OSError, ValueError):
                reason = "manifest_unreadable"
            else:
                if int(manifest.get("schema_version", -1)) > SCHEMA_VERSION:
                    reason = "schema_newer_than_reader"
                else:
                    try:
                        with open(ppath, "rb") as fh:
                            data = fh.read()
                    except OSError:
                        reason = "payload_missing"
                    else:
                        if _digest(data) != manifest.get("digest"):
                            reason = "digest_mismatch"
            if reason is None:
                legacy = int(manifest.get("schema_version", -1)) \
                    < SCHEMA_VERSION
                # an older-schema manifest restores fine (every v2 field
                # is optional-with-default) but the downgrade is COUNTED:
                # fleet telemetry sees how much of the fleet still runs
                # pre-cursor snapshots
                _publish("restore",
                         outcome="legacy_schema" if legacy else "ok",
                         seconds=time.perf_counter() - t0)
                return data.decode("utf-8"), manifest
            import warnings
            warnings.warn(
                f"checkpoint snapshot_{seq:08d} failed verification "
                f"({reason}); falling back to the previous snapshot",
                stacklevel=2)
            _publish("fallback", outcome=reason)
            if reason != "schema_newer_than_reader":
                # drop the corpse NOW: a corrupt snapshot left in place
                # would count toward keep-last-K retention and could evict
                # the valid previous snapshot on the next save (a newer-
                # schema snapshot is NOT a corpse — a newer reader may
                # still want it)
                self._remove(seq)
        _publish("restore", outcome="none",
                 seconds=time.perf_counter() - t0)
        return None

    # --------------------------------------------------------------- clear
    def clear(self) -> None:
        """Remove every snapshot (and orphaned payloads/tmp litter) — the
        crash artifacts of a now-completed fit."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return
        for n in names:
            if n.startswith((".snapshot_", "snapshot_")):
                try:
                    os.remove(os.path.join(self.directory, n))
                except OSError:
                    pass


# --------------------------------------------------------- preemption drain

#: default drain grace (seconds) — shared pools typically send SIGTERM
#: ~30 s before SIGKILL; override per-fit via the estimator param or
#: globally via this env var
DRAIN_GRACE_ENV = "MMLSPARK_TPU_DRAIN_GRACE_S"


class PreemptionDrain:
    """SIGTERM/SIGINT -> finish the in-flight chunk, snapshot, exit clean.

    Context manager installed for the duration of fit(). First signal:
    ``requested`` flips True (the chunk loop checks it at every chunk
    boundary and raises ``Preempted`` after the snapshot lands) and a
    watchdog timer is armed with the grace budget — if the drain cannot
    complete in time (a chunk longer than the pool's kill grace), the
    watchdog hard-exits with status 75 (EX_TEMPFAIL: retryable) rather
    than letting SIGKILL fall mid-write. Second signal: immediate
    ``KeyboardInterrupt`` (the operator insists).

    A signal that arrives too late to drain anything — during the FINAL
    chunk, or after early stopping — must not be swallowed: if the
    context exits with ``requested`` set but the drain never completed,
    ``__exit__`` re-delivers the signal to the process AFTER restoring
    the previous handlers, so the default disposition (or an outer
    handler) runs exactly as if the drain had never intercepted it. The
    just-finished fit's snapshots are still on disk at that point, so the
    re-delivered SIGTERM costs nothing: the next run resumes with zero
    remaining iterations and delivers the model instantly.

    Handlers install only in the main thread (signal.signal raises
    elsewhere); off-main-thread fits get a no-op drain, recorded on
    ``installed``. Previous handlers are restored on exit.
    """

    def __init__(self, grace_s: Optional[float] = None,
                 signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
                 on_grace_exceeded=None):
        if grace_s is None:
            grace_s = float(os.environ.get(DRAIN_GRACE_ENV, "30"))
        self.grace_s = float(grace_s)
        self.signals = tuple(signals)
        self._on_grace_exceeded = on_grace_exceeded or (lambda: os._exit(75))
        self._prev: Dict[int, Any] = {}
        self._watchdog: Optional[threading.Timer] = None
        self._requested_at: Optional[float] = None
        self._signum: Optional[int] = None
        self.installed = False
        self.drained = False

    # ------------------------------------------------------------- signals
    def _handler(self, signum, frame):
        if self._requested_at is not None:
            raise KeyboardInterrupt(
                f"second signal {signum} during drain — interrupting")
        self._requested_at = time.perf_counter()
        self._signum = signum
        _publish("drain_signal", outcome=f"sig{signum}")
        self._watchdog = threading.Timer(self.grace_s, self._grace_exceeded)
        self._watchdog.daemon = True
        self._watchdog.start()

    def _grace_exceeded(self):
        _publish("drain_grace_exceeded", outcome="hard_exit")
        self._on_grace_exceeded()

    @property
    def requested(self) -> bool:
        return self._requested_at is not None

    def completed(self) -> None:
        """The snapshot is on disk: disarm the watchdog and record the
        signal-to-safe duration."""
        if self._watchdog is not None:
            self._watchdog.cancel()
            self._watchdog = None
        if self._requested_at is not None and not self.drained:
            self.drained = True
            _publish("drain_complete",
                     seconds=time.perf_counter() - self._requested_at)

    # ------------------------------------------------------------- context
    def __enter__(self) -> "PreemptionDrain":
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handler)
            self.installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._watchdog is not None:
            self._watchdog.cancel()
            self._watchdog = None
        was_installed = self.installed
        for s, h in self._prev.items():
            signal.signal(s, h)
        self._prev.clear()
        self.installed = False
        if was_installed and self._signum is not None and not self.drained:
            # the signal landed but the loop finished before it could act
            # (final chunk / early stop): re-deliver under the restored
            # handlers instead of silently consuming an operator's Ctrl-C
            # or the pool's preemption notice
            _publish("drain_redelivered", outcome=f"sig{self._signum}")
            os.kill(os.getpid(), self._signum)
        return None
