"""Unified resilience layer: retries, deadlines, chaos, elastic recovery.

The single home for retry/backoff/deadline logic (reference:
FaultToleranceUtils, HandlingUtils.sendWithRetries, the rendezvous retry
loops). `io/http.py`, `models/deep/downloader.py`, `io/port_forwarding.py`,
the distributed-serving registration/heartbeat/gateway paths, and the bench
bring-up probe loop all route through here; tests/test_resilience.py lints
that no other module defines its own backoff loop.
"""

from .policy import (Attempt, Deadline, DeadlineExceeded, RetryError,
                     RetryPolicy, parse_retry_after)
from .chaos import (FaultInjector, InjectedDrop, InjectedFault, InjectedKill,
                    RewardFaultInjector, TrainingFaultInjector, derive_seed)
from .bringup import backend_bringup
from .rewardjoin import RewardJoiner, REFUSAL_REASONS
from .elastic import (CheckpointStore, Preempted, PreemptionDrain,
                      atomic_write_bytes, atomic_write_text)
from .scenario import (Phase, ScenarioChaos, ScenarioEngine,
                       ScenarioTimeline, Scorecard, build_scorecard,
                       cost_proxy, diurnal_phases, judge_slo,
                       reconcile_chaos)

__all__ = [
    "Attempt", "Deadline", "DeadlineExceeded", "RetryError", "RetryPolicy",
    "parse_retry_after",
    "FaultInjector", "InjectedDrop", "InjectedFault", "InjectedKill",
    "RewardFaultInjector", "TrainingFaultInjector", "derive_seed",
    "backend_bringup",
    "RewardJoiner", "REFUSAL_REASONS",
    "CheckpointStore", "Preempted", "PreemptionDrain",
    "atomic_write_bytes", "atomic_write_text",
    "Phase", "ScenarioChaos", "ScenarioEngine", "ScenarioTimeline",
    "Scorecard", "build_scorecard", "cost_proxy", "diurnal_phases",
    "judge_slo", "reconcile_chaos",
]
