"""Production-day scenario engine (ISSUE 20, ROADMAP item 6).

Every resilience subsystem was proved one fault at a time (load chaos,
swap/autoscale, host loss, reward storms); this module is the
COMPOSITION: a replayable "production day" — seeded diurnal traffic
(ramp -> peak -> burst -> trough), a scripted event timeline on ONE
injected clock (canary rollout at peak, worker kill mid-rollout, corrupt
artifact publish, autoscale-down in the trough, online-learner
preemption via the PR 19 loop), and a machine-checkable scorecard. The
same engine drives both the tier-1 mini run (injected clock, in-process
fakes, compressed timeline — tests/test_production_day.py) and the
full-length fleet run (scripts/run_production_day.py composing the
io/loadgen.py legs), so the scorecard logic is proved once and reused.

The pieces:

- `diurnal_phases(total_s)` — the canonical four-phase day with per-phase
  traffic levels; `burst` is judged for SLO adherence but exempt from
  gating (a flash crowd MAY shed within the error budget).
- `ScenarioTimeline` — scripted actions at scenario-time offsets, fired
  once by `poll(now_s)` in order; an action's exception is recorded, not
  propagated (the day continues, the scorecard judges).
- `ScenarioChaos` — one master seed derives every sub-injector via
  `chaos.derive_seed(seed, name)` (the replay contract), and scripted
  faults (worker kill, corrupt artifact, learner preemption) are
  recorded at their DESIGNATED commit points: independent ground-truth
  counts + `scenario_injected_faults_total{kind}` + a `chaos` system
  event on the fleet ring (so the flight recorder's chaos trigger dumps
  one forensics bundle per fault class).
- `Scorecard` — named checks counted into
  `scenario_scorecard_checks_total{check,outcome}`; `exempt` checks are
  judged and recorded but do not gate `passed`.
- `ScenarioEngine` — the phase loop on an injected (clock, sleep) pair:
  publishes the `scenario_phase` gauge at phase transitions (a
  designated commit point, never the hot traffic path), fires due
  timeline actions, and calls the per-tick sampler.
- `build_scorecard(...)` — the one shared judgment: per-phase SLO
  adherence from the PR 14 monitors, zero accepted-request loss, one
  incident bundle per injected fault class, EXACT chaos reconciliation
  against injector ground truth, the worker-seconds cost proxy vs the
  no-autoscaler baseline leg, and fault-schedule determinism (the
  re-derived schedule digest must match).
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from .chaos import (FaultInjector, RewardFaultInjector,
                    TrainingFaultInjector, derive_seed)

__all__ = [
    "PHASE_ORDER", "Phase", "diurnal_phases", "ScenarioTimeline",
    "ScenarioChaos", "Scorecard", "ScenarioEngine", "judge_slo",
    "worker_seconds", "cost_proxy", "reconcile_chaos", "build_scorecard",
]

PHASE_ORDER = ("ramp", "peak", "burst", "trough")


@dataclass
class Phase:
    """One diurnal phase: a traffic level held for a duration."""
    name: str
    duration_s: float
    traffic: float                 # fraction of peak traffic (1.0 = peak)
    slo_required: bool = True      # False = judged but not gating (burst)
    start_s: float = 0.0           # filled by diurnal_phases

    @property
    def end_s(self) -> float:
        return self.start_s + self.duration_s


def diurnal_phases(total_s: float,
                   burst_factor: float = 1.25) -> List[Phase]:
    """The canonical production day, scaled to `total_s`: ramp (40% of
    peak traffic, 25% of the day) -> peak (100%, 30%) -> burst (the
    flash crowd riding on top of peak, 15%) -> trough (15% traffic, 30%).
    Burst is judged for SLO adherence but exempt from gating: shedding
    inside the error budget is the DESIGNED response to a flash crowd."""
    fracs = {"ramp": 0.25, "peak": 0.30, "burst": 0.15, "trough": 0.30}
    traffic = {"ramp": 0.4, "peak": 1.0, "burst": float(burst_factor),
               "trough": 0.15}
    phases: List[Phase] = []
    t = 0.0
    for name in PHASE_ORDER:
        p = Phase(name, total_s * fracs[name], traffic[name],
                  slo_required=(name != "burst"), start_s=t)
        t += p.duration_s
        phases.append(p)
    return phases


class ScenarioTimeline:
    """Scripted actions at scenario-time offsets, fired once, in order.

    `poll(now_s)` fires every not-yet-fired action whose offset has
    passed. An action that raises is RECORDED (the `error` field) and
    the day continues — a production day does not stop because one
    scripted event misfired; the scorecard judges the aftermath."""

    def __init__(self):
        self._actions: List[Dict[str, Any]] = []
        self.fired: List[Dict[str, Any]] = []

    def at(self, at_s: float, name: str,
           fn: Callable[[], Any]) -> "ScenarioTimeline":
        self._actions.append({"at_s": float(at_s), "name": name,
                              "fn": fn, "fired": False})
        self._actions.sort(key=lambda a: a["at_s"])
        return self

    def poll(self, now_s: float) -> List[str]:
        fired_now: List[str] = []
        for a in self._actions:
            if a["fired"] or a["at_s"] > now_s:
                continue
            a["fired"] = True
            rec = {"name": a["name"], "at_s": round(a["at_s"], 2),
                   "fired_s": round(now_s, 2), "error": None}
            try:
                a["fn"]()
            except Exception as e:  # noqa: BLE001 - the day continues
                rec["error"] = f"{type(e).__name__}: {e}"[:200]
            self.fired.append(rec)
            fired_now.append(a["name"])
        return fired_now

    @property
    def pending(self) -> List[str]:
        return [a["name"] for a in self._actions if not a["fired"]]


class ScenarioChaos:
    """One master seed -> every sub-injector + scripted-fault ground truth.

    Sub-injectors come from the `from_master` constructors (seed =
    `derive_seed(master_seed, name)`), so the whole multi-plane fault
    schedule replays from a single number. Scripted faults (worker kill,
    corrupt artifact, learner preemption — events the timeline fires, not
    probability draws) are recorded through `record_scripted`, the
    designated commit point: the independent `scripted` tally, the
    `scenario_injected_faults_total{kind}` counter, and a `chaos` system
    event on the fleet ring (the flight recorder's chaos trigger turns
    it into a per-fault-class incident bundle)."""

    def __init__(self, master_seed: int, registry=None, event_log=None):
        self.master_seed = int(master_seed)
        self.registry = registry
        self.event_log = event_log
        self.injectors: Dict[str, Any] = {}
        self.scripted: Dict[str, int] = {}

    # ------------------------------------------------------- sub-injectors
    def fault_injector(self, name: str, **kw) -> FaultInjector:
        inj = FaultInjector.from_master(self.master_seed, name, **kw)
        self.injectors[name] = inj
        return inj

    def training_injector(self, name: str, **kw) -> TrainingFaultInjector:
        inj = TrainingFaultInjector.from_master(self.master_seed, name,
                                                **kw)
        self.injectors[name] = inj
        return inj

    def reward_injector(self, name: str, **kw) -> RewardFaultInjector:
        inj = RewardFaultInjector.from_master(self.master_seed, name, **kw)
        self.injectors[name] = inj
        return inj

    # ------------------------------------------------------ scripted faults
    def record_scripted(self, kind: str, **detail) -> None:
        self.scripted[kind] = self.scripted.get(kind, 0) + 1
        if self.registry is not None:
            try:
                self.registry.counter(
                    "scenario_injected_faults_total",
                    "scripted production-day faults by kind",
                    labels={"kind": kind}).inc()
            except Exception:  # noqa: BLE001 - telemetry must not alter chaos
                pass
        if self.event_log is not None:
            try:
                self.event_log.append("chaos", kind=kind,
                                      seed=self.master_seed, scripted=True,
                                      **detail)
            except Exception:  # noqa: BLE001 - tracing must not alter chaos
                pass

    # -------------------------------------------------------- replay proof
    def schedule(self, n: int = 32) -> Dict[str, Any]:
        """The whole run's fault plan as data: per-injector derived seed +
        schedule preview (probability injectors) or kill boundary
        (training injectors). A pure function of (master_seed, the
        injector names and rates) — the replay contract."""
        out: Dict[str, Any] = {"master_seed": self.master_seed,
                               "injectors": {}}
        for name, inj in sorted(self.injectors.items()):
            rec: Dict[str, Any] = {
                "seed": derive_seed(self.master_seed, name)}
            if isinstance(inj, TrainingFaultInjector):
                rec["kill_at_chunk"] = inj.kill_at_chunk
            else:
                rec["schedule"] = inj.schedule(n)
            out["injectors"][name] = rec
        return out

    def schedule_digest(self, n: int = 32) -> str:
        payload = json.dumps(self.schedule(n), sort_keys=True,
                             separators=(",", ":")).encode()
        return "sha256:" + hashlib.sha256(payload).hexdigest()


class Scorecard:
    """Named machine-checkable verdicts, counted into
    `scenario_scorecard_checks_total{check,outcome}` at the single
    designated commit point (`check()`). `exempt` checks are judged and
    recorded but excluded from `passed` — the burst phase's SLO verdict
    is information, not a gate."""

    def __init__(self, registry=None):
        self.registry = registry
        self.checks: List[Dict[str, Any]] = []

    def check(self, name: str, ok: bool, detail: str = "",
              exempt: bool = False) -> bool:
        ok = bool(ok)
        self.checks.append({"check": name, "ok": ok,
                            "detail": str(detail)[:300],
                            "exempt": bool(exempt)})
        if self.registry is not None:
            try:
                self.registry.counter(
                    "scenario_scorecard_checks_total",
                    "scorecard checks by outcome",
                    labels={"check": name,
                            "outcome": "pass" if ok else "fail"}).inc()
            except Exception:  # noqa: BLE001 - telemetry never fails a check
                pass
        return ok

    @property
    def passed(self) -> bool:
        return all(c["ok"] for c in self.checks if not c["exempt"])

    def as_dict(self) -> Dict[str, Any]:
        return {"passed": self.passed,
                "checks_total": len(self.checks),
                "checks_failed": sum(1 for c in self.checks
                                     if not c["ok"] and not c["exempt"]),
                "checks": list(self.checks)}


class ScenarioEngine:
    """The phase loop on one injected (clock, sleep) pair.

    Per phase: publish the `scenario_phase` gauge (phase transition — a
    designated commit point), call `on_phase` (the traffic controller),
    then tick until the phase's scenario-time budget is spent, firing due
    timeline actions and the per-tick sampler. The mini run passes a
    fake clock whose `sleep` advances it (compressed timeline, zero real
    waiting); the full run passes `time.monotonic`/`time.sleep`."""

    def __init__(self, phases: Sequence[Phase],
                 timeline: Optional[ScenarioTimeline] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 tick_s: float = 0.5, registry=None,
                 on_phase: Optional[Callable[[Phase], None]] = None,
                 on_tick: Optional[Callable[[Phase], None]] = None):
        self.phases = list(phases)
        self.timeline = timeline if timeline is not None \
            else ScenarioTimeline()
        self.clock = clock
        self.sleep = sleep
        self.tick_s = float(tick_s)
        self.registry = registry
        self.on_phase = on_phase
        self.on_tick = on_tick
        self.phase_log: List[Dict[str, Any]] = []
        self._t0: Optional[float] = None

    def now(self) -> float:
        """Scenario time (seconds since run() started)."""
        if self._t0 is None:
            return 0.0
        return self.clock() - self._t0

    def run(self) -> List[Dict[str, Any]]:
        self._t0 = self.clock()
        gauge = None
        if self.registry is not None:
            gauge = self.registry.gauge(
                "scenario_phase",
                "active production-day phase index (0-based)")
        for i, phase in enumerate(self.phases):
            if gauge is not None:
                gauge.set(i)    # the phase-transition commit point
            if self.on_phase is not None:
                self.on_phase(phase)
            self.phase_log.append({"phase": phase.name, "index": i,
                                   "started_s": round(self.now(), 2)})
            while self.now() < phase.end_s - 1e-9:
                self.timeline.poll(self.now())
                if self.on_tick is not None:
                    self.on_tick(phase)
                self.sleep(self.tick_s)
            self.phase_log[-1]["ended_s"] = round(self.now(), 2)
        self.timeline.poll(self.now())   # trailing actions fire at day end
        return self.phase_log


# --------------------------------------------------------------- judgments

def judge_slo(samples: Sequence[Optional[Dict[str, Any]]]) -> Dict[str, Any]:
    """Phase SLO adherence from SLOMonitor.status() samples collected
    during the phase: adherent iff no sample showed a breached SLO.
    Warm-up samples (burn None) count as adherent — the monitor refuses
    to judge a window it has not seen half of."""
    breached: set = set()
    n = 0
    for s in samples or ():
        if not s:
            continue
        n += 1
        for slo_name, st in s.items():
            if st.get("breached"):
                breached.add(str(slo_name))
    return {"samples": n, "breached_slos": sorted(breached),
            "adherent": not breached}


def worker_seconds(series: Sequence[Dict[str, Any]],
                   end_s: float) -> float:
    """Step-integral of fleet size over scenario time. `series`:
    [{"t": scenario_s, "workers": n}, ...] in time order; each sample's
    size is held until the next sample (the last until `end_s`)."""
    total = 0.0
    pts = [s for s in series if "t" in s and "workers" in s]
    for i, s in enumerate(pts):
        t_next = pts[i + 1]["t"] if i + 1 < len(pts) else float(end_s)
        total += max(0.0, t_next - s["t"]) * s["workers"]
    return total


def cost_proxy(series: Sequence[Dict[str, Any]], end_s: float,
               baseline_workers: int) -> Dict[str, Any]:
    """Worker-seconds with the autoscaler vs the no-autoscaler baseline
    leg: static provisioning must hold the PEAK fleet all day (that is
    what "no autoscaler" costs — you provision for the worst phase)."""
    ws = worker_seconds(series, end_s)
    baseline = float(baseline_workers) * float(end_s)
    return {
        "worker_seconds": round(ws, 1),
        "baseline_workers": int(baseline_workers),
        "baseline_worker_seconds": round(baseline, 1),
        "saved_worker_seconds": round(baseline - ws, 1),
        "saved_frac": round((baseline - ws) / baseline, 4) if baseline
        else 0.0,
    }


def reconcile_chaos(chaos: ScenarioChaos, registry) -> Dict[str, Any]:
    """EXACT reconciliation of telemetry counters against ground truth
    that does not share the registry's code path: per fault kind, the
    registry's `chaos_injected_total{kind}` (probability injectors,
    train kills) or `scenario_injected_faults_total{kind}` (scripted
    faults) must equal the injector's own tally. Inexact is a FINDING
    (lost or double-counted fault), never a rounding allowance."""
    rows: List[Dict[str, Any]] = []

    def reg_value(family: str, kind: str) -> float:
        return registry.counter(family, labels={"kind": kind}).value

    for name, inj in sorted(chaos.injectors.items()):
        if isinstance(inj, FaultInjector):
            kinds = [("error", inj.error_rate), ("drop", inj.drop_rate),
                     ("delay", inj.delay_rate)]
            for kind, rate in kinds:
                if rate <= 0.0:
                    continue
                truth = inj.counts[kind]
                seen = reg_value("chaos_injected_total", kind)
                rows.append({"injector": name, "kind": kind,
                             "ground_truth": truth, "registry": seen,
                             "exact": seen == truth})
        elif isinstance(inj, TrainingFaultInjector):
            truth = inj.counts["kills"]
            seen = reg_value("chaos_injected_total", "train_kill")
            rows.append({"injector": name, "kind": "train_kill",
                         "ground_truth": truth, "registry": seen,
                         "exact": seen == truth})
        elif isinstance(inj, RewardFaultInjector):
            for kind in ("duplicate_reward", "delay_reward",
                         "drop_reward"):
                truth = inj.counts[kind]
                if truth == 0 and getattr(
                        inj, kind.split("_")[0] + "_rate", 0.0) <= 0.0:
                    continue
                seen = reg_value("chaos_injected_total", kind)
                rows.append({"injector": name, "kind": kind,
                             "ground_truth": truth, "registry": seen,
                             "exact": seen == truth})
    for kind, truth in sorted(chaos.scripted.items()):
        seen = reg_value("scenario_injected_faults_total", kind)
        rows.append({"injector": "scripted", "kind": kind,
                     "ground_truth": truth, "registry": seen,
                     "exact": seen == truth})
    return {"rows": rows, "exact": all(r["exact"] for r in rows)}


def fault_classes(chaos: ScenarioChaos) -> List[str]:
    """Every fault class actually injected this run (count > 0): the
    scripted kinds plus each probability injector's fired kinds. Each
    must have produced its `chaos_<kind>` flight-recorder bundle."""
    kinds = {k for k, v in chaos.scripted.items() if v > 0}
    for inj in chaos.injectors.values():
        if isinstance(inj, FaultInjector):
            for kind in ("error", "drop", "delay"):
                if inj.counts[kind] > 0:
                    kinds.add(kind)
        elif isinstance(inj, RewardFaultInjector):
            for kind in ("duplicate_reward", "delay_reward",
                         "drop_reward"):
                if inj.counts[kind] > 0:
                    kinds.add(kind)
    return sorted(kinds)


def build_scorecard(*, registry, phases: Sequence[Phase],
                    phase_slo: Dict[str, Dict[str, Any]],
                    tallies: Dict[str, Any],
                    incident_reasons: Sequence[str],
                    chaos: ScenarioChaos,
                    cost: Dict[str, Any],
                    schedule_digest: str) -> Scorecard:
    """The one shared judgment, identical between the tier-1 mini run and
    the full fleet run (the acceptance contract in ISSUE 20):

    1. every phase's SLO adherence judged (burst exempt from gating),
    2. zero accepted-request loss across all injected faults,
    3. >= 1 flight-recorder incident bundle per injected fault class,
    4. chaos counters reconciled EXACTLY against injector ground truth,
    5. the worker-seconds cost proxy beats the no-autoscaler baseline,
    6. the fault schedule replays from the master seed (digest match).
    """
    sc = Scorecard(registry)
    for ph in phases:
        rep = phase_slo.get(ph.name) or {"samples": 0, "breached_slos": [],
                                         "adherent": False}
        sc.check(f"slo_phase_{ph.name}", rep["adherent"],
                 detail=(f"{rep['samples']} samples"
                         + (f", breached: {rep['breached_slos']}"
                            if rep["breached_slos"] else "")),
                 exempt=not ph.slo_required)
    bad = int(tallies.get("bad_payload_on_200", 0))
    lost = int(tallies.get("no_reply_lost", 0))
    sc.check("zero_accepted_loss", bad == 0 and lost == 0,
             detail=f"bad_payload_on_200={bad} no_reply_lost={lost} over "
                    f"{tallies.get('client_requests', 0)} requests")
    reasons = set(incident_reasons)
    for kind in fault_classes(chaos):
        sc.check(f"bundle_{kind}", f"chaos_{kind}" in reasons,
                 detail=f"flight-recorder bundle chaos_{kind} "
                        + ("present" if f"chaos_{kind}" in reasons
                           else f"MISSING (have {sorted(reasons)})"))
    rec = reconcile_chaos(chaos, registry)
    for row in rec["rows"]:
        sc.check(f"chaos_reconcile_{row['kind']}", row["exact"],
                 detail=f"{row['injector']}: ground truth "
                        f"{row['ground_truth']} vs registry "
                        f"{row['registry']:.0f}")
    sc.check("cost_beats_no_autoscaler_baseline",
             cost["worker_seconds"] < cost["baseline_worker_seconds"],
             detail=f"{cost['worker_seconds']} worker-s with autoscaler vs "
                    f"{cost['baseline_worker_seconds']} static at peak "
                    f"({cost['baseline_workers']} workers)")
    sc.check("fault_schedule_deterministic",
             chaos.schedule_digest() == schedule_digest,
             detail=f"re-derived {chaos.schedule_digest()[:23]}... vs "
                    f"planned {schedule_digest[:23]}...")
    return sc
