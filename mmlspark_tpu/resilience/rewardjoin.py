"""Durable reward-prediction joining for the train-on-traffic loop.

The hard half of ROADMAP item 2 is not moving examples fast (PR 16 did
that) but surviving what a production reward stream actually delivers:
duplicate reward events (at-least-once transports re-send), late rewards
(conversion signals arrive minutes after the prediction), out-of-order
arrival (a reward can beat its own prediction record through the log),
and worker death mid-join. ``RewardJoiner`` turns that stream into
examples that are applied to the learner **exactly once**:

- **Keyed on X-Trace-Id**: the serving plane already mints/propagates a
  trace id per request (PR 8); the served-prediction event and its
  delayed reward event share it, so the join key is free.
- **Bounded, spillable buffer**: pending predictions wait in memory up
  to ``max_pending_mem`` records; overflow spills payloads to disk
  (append-only JSONL spill files, a key->(file, offset) index in RAM).
  All other structures hold only keys + timestamps. RAM is
  O(max_pending_mem payloads + horizon-window keys), never O(stream).
- **Idempotent dedup**: applied keys live in a seen ring, evicted only
  once the event-time watermark passes ``horizon_s`` beyond them — any
  duplicate inside the horizon is refused, and a duplicate OUTSIDE the
  horizon is refused by the horizon itself (expired/unknown). Late and
  out-of-order rewards therefore apply exactly once or are refused with
  a COUNTED reason, never applied twice and never silently dropped.
- **Counted refusal vocabulary** (docs/ONLINE.md): ``duplicate`` /
  ``duplicate_prediction`` (key already applied or in flight),
  ``expired`` (reward landed after its prediction's horizon),
  ``unknown_key`` (reward whose prediction never arrived within the
  horizon), ``reward_timeout`` (prediction evicted with no reward),
  ``malformed`` (event missing required fields). ``self.counts`` stays
  an INDEPENDENT tally beside the ``online_join_refusals_total``
  registry family — chaos tests reconcile the two exactly, like the
  transport-fault injectors do.
- **Deterministic**: all expiry decisions run on the EVENT-TIME
  watermark (max PREDICTION ts ingested — the served-traffic clock,
  monotone with the stream), never the wall clock, so replaying the
  same event log yields the identical join/refusal sequence — the
  property the online loop's preempt-resume digest-parity proof
  (train/online_loop.py) is built on. A reward timestamp enters only
  the per-pair lateness decision (reward.ts - prediction.ts > horizon
  => expired), so a far-future reward ts expires its OWN join without
  flushing every other in-flight prediction.
- **Snapshot/restore**: ``snapshot_state()`` captures the full join
  state (pending payloads incl. spilled, dedup rings, counters,
  watermark) as one JSON-able dict, persisted by the loop through the
  PR 10 ``CheckpointStore``; ``restore_state`` rebuilds it. Snapshot
  size is O(pending-within-horizon), the same bound as RAM.
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional

__all__ = ["RewardJoiner", "REFUSAL_REASONS"]

#: the documented refusal vocabulary (docs/ONLINE.md); every refusal is
#: counted under exactly one of these reasons
REFUSAL_REASONS = ("duplicate", "duplicate_prediction", "expired",
                   "unknown_key", "reward_timeout", "malformed")

#: IPS weight cap — mirrors the offline contextual-bandit fit
#: (models/vw/contextual_bandit.py: min(1/max(p, 1e-6), 1e3))
IPS_WEIGHT_CAP = 1e3


def _publish_refusal(reason: str) -> None:
    try:
        from ..observability import bridge as obsbridge
        obsbridge.publish_online_refusal(reason)
    except Exception:  # noqa: BLE001 - telemetry never alters the join
        pass


def _publish_event(kind: str) -> None:
    try:
        from ..observability import bridge as obsbridge
        obsbridge.publish_online_event(kind)
    except Exception:  # noqa: BLE001
        pass


class _SpillStore:
    """Append-only JSONL spill files for overflow prediction payloads.

    Not independently durable (plain appends): the snapshot — which
    reads spilled payloads back — is the durability story; the spill
    exists solely to bound RAM between snapshots. Files rotate every
    ``rotate`` records and are deleted once every record in them has
    been joined or evicted."""

    def __init__(self, directory: str, rotate: int = 4096):
        self.directory = directory
        self.rotate = int(rotate)
        self._file_seq = 0
        self._records_in_current = 0
        self._live: Dict[int, int] = {}  # file_seq -> live record count
        self.spilled = 0
        self.read_back = 0

    def _path(self, seq: int) -> str:
        return os.path.join(self.directory, f"spill_{seq:06d}.jsonl")

    def append(self, record: Dict[str, Any]):
        """Returns (file_seq, byte_offset) for the index."""
        os.makedirs(self.directory, exist_ok=True)
        if self._records_in_current >= self.rotate:
            self._file_seq += 1
            self._records_in_current = 0
        seq = self._file_seq
        path = self._path(seq)
        line = (json.dumps(record, sort_keys=True) + "\n").encode("utf-8")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            offset = os.lseek(fd, 0, os.SEEK_END)
            os.write(fd, line)
        finally:
            os.close(fd)
        self._records_in_current += 1
        self._live[seq] = self._live.get(seq, 0) + 1
        self.spilled += 1
        return seq, offset

    def read(self, seq: int, offset: int) -> Dict[str, Any]:
        with open(self._path(seq), "rb") as fh:
            fh.seek(offset)
            self.read_back += 1
            return json.loads(fh.readline())

    def release(self, seq: int) -> None:
        """One record in file `seq` is dead; delete the file when all are
        (a file the current writer still appends to is kept)."""
        n = self._live.get(seq, 0) - 1
        if n > 0:
            self._live[seq] = n
            return
        self._live.pop(seq, None)
        if seq != self._file_seq:
            try:
                os.remove(self._path(seq))
            except OSError:
                pass


class RewardJoiner:
    """Match served predictions to delayed rewards, exactly once.

    Event schema (JSONL records, io/streaming.JsonlEventSource):

    - prediction: ``{"kind": "prediction", "key": <trace id>, "ts": t,
      "indices": [...], "values": [...], "probability": p?}`` — the
      hashed (shared ⊕ chosen-action) feature row the serving client
      logged, plus the logged exploration probability (IPS weight
      ``min(1/max(p, 1e-6), 1e3)``, the offline bandit fit's cap).
    - reward: ``{"kind": "reward", "key": <trace id>, "ts": t,
      "cost": c}`` — lower cost is better (VW CB convention).

    ``ingest(event)`` returns the joined example when this event
    completed a join, else None. Every non-join outcome is counted.
    """

    def __init__(self, *, horizon_s: float = 300.0,
                 max_pending_mem: int = 4096,
                 spill_dir: Optional[str] = None,
                 max_tracked_keys: int = 1 << 20):
        if horizon_s <= 0:
            raise ValueError(f"horizon_s must be > 0, got {horizon_s}")
        if max_pending_mem < 1:
            raise ValueError("max_pending_mem must be >= 1")
        self.horizon_s = float(horizon_s)
        self.max_pending_mem = int(max_pending_mem)
        self.max_tracked_keys = int(max_tracked_keys)
        self._spill = (_SpillStore(spill_dir) if spill_dir else None)
        self.spill_dir = spill_dir
        # key -> full prediction record (insertion = ts order for evict)
        self._pending_mem: "OrderedDict[str, Dict]" = OrderedDict()
        # key -> (file_seq, offset, ts) for spilled predictions
        self._pending_spilled: "OrderedDict[str, tuple]" = OrderedDict()
        # rewards that arrived before their prediction (out-of-order)
        self._pending_rewards: "OrderedDict[str, Dict]" = OrderedDict()
        # applied keys (dedup ring) and evicted-prediction keys (so a late
        # reward is refused "expired", not "unknown_key") — key -> ts
        self._seen: "OrderedDict[str, float]" = OrderedDict()
        self._expired: "OrderedDict[str, float]" = OrderedDict()
        self.watermark = float("-inf")
        #: independent ground-truth tally (reconciled against the
        #: online_* registry families in tests, never derived from them)
        self.counts: Dict[str, int] = {
            "predictions": 0, "rewards": 0, "joined": 0,
            **{r: 0 for r in REFUSAL_REASONS}}

    # ----------------------------------------------------------- pending
    @property
    def pending_predictions(self) -> int:
        return len(self._pending_mem) + len(self._pending_spilled)

    @property
    def pending_rewards(self) -> int:
        return len(self._pending_rewards)

    def _refuse(self, reason: str) -> None:
        self.counts[reason] += 1
        _publish_refusal(reason)

    # ----------------------------------------------------------- watermark
    def _advance_watermark(self, ts: float) -> None:
        if ts <= self.watermark:
            return
        self.watermark = ts
        limit = ts - self.horizon_s
        # predictions past the horizon: no reward is coming (or it will
        # be refused as expired) — evict, counted
        for pend in (self._pending_mem, self._pending_spilled):
            while pend:
                key, rec = next(iter(pend.items()))
                rts = rec["ts"] if isinstance(rec, dict) else rec[2]
                if rts >= limit:
                    break
                pend.popitem(last=False)
                if pend is self._pending_spilled and self._spill:
                    self._spill.release(rec[0])
                # stamped with the EVICTION watermark (not the stale
                # prediction ts): the expired marker must itself survive
                # one more horizon so a late reward is refused "expired",
                # not misfiled as "unknown_key"
                self._expired[key] = ts
                self._refuse("reward_timeout")
        # orphan rewards past the horizon: the prediction never arrived
        while self._pending_rewards:
            key, rec = next(iter(self._pending_rewards.items()))
            if rec["ts"] >= limit:
                break
            self._pending_rewards.popitem(last=False)
            self._refuse("unknown_key")
        # dedup rings only need to cover the horizon window: any event
        # for an older key is refused by the horizon itself
        for ring in (self._seen, self._expired):
            while ring:
                key, rts = next(iter(ring.items()))
                if rts >= limit and len(ring) <= self.max_tracked_keys:
                    break
                ring.popitem(last=False)

    def advance(self, ts: float) -> None:
        """Advance the event-time watermark without an event (an
        end-of-stream close or idle tick): expiries fire exactly as if
        an event with this ts had arrived. Chaos reconciliation uses it
        to flush the tail — a dropped reward's prediction only counts
        its `reward_timeout` once the watermark passes its horizon."""
        self._advance_watermark(float(ts))

    # -------------------------------------------------------------- ingest
    def ingest(self, event: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Process one event; returns the joined training example iff this
        event completed a join. HOT PATH: pure host-side dict/list work —
        nothing here may touch a device value (AST sync-point lint)."""
        kind = event.get("kind")
        key = event.get("key")
        ts = event.get("ts")
        if kind not in ("prediction", "reward") or not key or ts is None:
            self._refuse("malformed")
            return None
        ts = float(ts)
        if kind == "prediction":
            # the watermark advances on PREDICTION timestamps only — the
            # served-traffic clock, monotone with the stream. A reward
            # timestamp enters only the per-pair lateness decision: a
            # wildly future reward ts (the delay fault) must expire ITS
            # join, not flush every other in-flight prediction
            self._advance_watermark(ts)
        _publish_event(kind)
        if kind == "prediction":
            return self._ingest_prediction(key, ts, event)
        return self._ingest_reward(key, ts, event)

    def ingest_batch(self, events) -> List[Dict[str, Any]]:
        out = []
        for ev in events:
            j = self.ingest(ev)
            if j is not None:
                out.append(j)
        return out

    def _ingest_prediction(self, key: str, ts: float,
                           event: Dict[str, Any]) -> Optional[Dict]:
        self.counts["predictions"] += 1
        if "indices" not in event or "values" not in event:
            self._refuse("malformed")
            return None
        if key in self._seen or key in self._pending_mem \
                or key in self._pending_spilled:
            self._refuse("duplicate_prediction")
            return None
        reward = self._pending_rewards.pop(key, None)
        if reward is not None:
            # out-of-order arrival: the reward beat its prediction here
            return self._join(event, reward)
        self._pending_mem[key] = event
        if len(self._pending_mem) > self.max_pending_mem:
            self._spill_oldest()
        return None

    def _ingest_reward(self, key: str, ts: float,
                       event: Dict[str, Any]) -> Optional[Dict]:
        self.counts["rewards"] += 1
        if "cost" not in event:
            self._refuse("malformed")
            return None
        if key in self._seen:
            self._refuse("duplicate")
            return None
        if key in self._expired:
            self._refuse("expired")
            return None
        pred = self._pending_mem.pop(key, None)
        if pred is None and key in self._pending_spilled:
            seq, offset, _rts = self._pending_spilled.pop(key)
            pred = self._spill.read(seq, offset)
            self._spill.release(seq)
        if pred is None:
            if key in self._pending_rewards:
                self._refuse("duplicate")
                return None
            self._pending_rewards[key] = event
            return None
        if ts - float(pred["ts"]) > self.horizon_s:
            # late beyond the horizon with the prediction still buffered
            # (watermark had not passed it yet): same contract — refused
            self._expired[key] = self.watermark
            self._refuse("expired")
            return None
        return self._join(pred, event)

    def _join(self, pred: Dict[str, Any],
              reward: Dict[str, Any]) -> Dict[str, Any]:
        key = pred["key"]
        self._seen[key] = max(float(pred["ts"]), float(reward["ts"]))
        self.counts["joined"] += 1
        p = float(pred.get("probability", 1.0))
        return {
            "key": key,
            "indices": pred["indices"],
            "values": pred["values"],
            "label": float(reward["cost"]),
            "weight": min(1.0 / max(p, 1e-6), IPS_WEIGHT_CAP),
            "pred_ts": float(pred["ts"]),
            "reward_ts": float(reward["ts"]),
        }

    def _spill_oldest(self) -> None:
        """RAM bound: move the oldest in-memory prediction payload to the
        spill store, keeping only (file, offset, ts) in memory."""
        key, rec = self._pending_mem.popitem(last=False)
        if self._spill is None:
            # no spill dir configured: the bound still holds — the
            # overflow prediction is evicted as if timed out (counted,
            # never unbounded memory)
            self._expired[key] = self.watermark
            self._refuse("reward_timeout")
            return
        seq, offset = self._spill.append(rec)
        self._pending_spilled[key] = (seq, offset, float(rec["ts"]))

    # ---------------------------------------------------------- snapshot
    def snapshot_state(self) -> Dict[str, Any]:
        """Full join state as one JSON-able dict (spilled payloads read
        back in). Paired with the event-log cursor in the loop snapshot:
        restore + seek(cursor) + replay == never-interrupted ingest."""
        spilled = []
        for key, (seq, offset, _ts) in self._pending_spilled.items():
            spilled.append(self._spill.read(seq, offset))
        return {
            "horizon_s": self.horizon_s,
            "watermark": (None if self.watermark == float("-inf")
                          else self.watermark),
            "pending_predictions": (list(self._pending_mem.values())
                                    + spilled),
            "pending_rewards": list(self._pending_rewards.values()),
            "seen": list(self._seen.items()),
            "expired": list(self._expired.items()),
            "counts": dict(self.counts),
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        """Rebuild from `snapshot_state()` output. Pending predictions
        re-enter through the normal bound (re-spilling overflow), so a
        restore never exceeds the RAM bound either."""
        if float(state.get("horizon_s", self.horizon_s)) != self.horizon_s:
            raise ValueError(
                f"snapshot horizon {state.get('horizon_s')}s != configured "
                f"{self.horizon_s}s — the dedup rings' eviction contract "
                f"depends on the horizon; refusing a silent change")
        self.watermark = (float("-inf") if state.get("watermark") is None
                          else float(state["watermark"]))
        self._pending_mem.clear()
        self._pending_spilled.clear()
        self._pending_rewards.clear()
        for rec in state.get("pending_predictions", []):
            self._pending_mem[rec["key"]] = rec
            if len(self._pending_mem) > self.max_pending_mem:
                self._spill_oldest()
        for rec in state.get("pending_rewards", []):
            self._pending_rewards[rec["key"]] = rec
        self._seen = OrderedDict(
            (k, float(v)) for k, v in state.get("seen", []))
        self._expired = OrderedDict(
            (k, float(v)) for k, v in state.get("expired", []))
        self.counts.update({k: int(v)
                            for k, v in state.get("counts", {}).items()})
