"""Patient, bounded accelerator bring-up behind the shared RetryPolicy.

Factored out of bench.py (round-3 verdict #1; the probe history appears as
`bringup_probes` in every BENCH_r*.json). The shared device pool has two
measured failure modes (docs/tpu_watch.log, rounds 2-3): fast UNAVAILABLE
errors, and init hangs that clear in ~25 min after a killed client wedged
the pool's grant. Discipline (revised per round-5 verdict #1 — a single
probe left hanging for the whole 1320 s budget was the direct cause of
five consecutive CPU-fallback scoreboards):

- probe for up to the wall budget, sleeping a jittered `retry_sleep_s`
  between failed attempts (RetryPolicy owns the sleeping and the
  don't-spawn-a-doomed-probe cutoff via `min_attempt_s`);
- cap EACH probe at `max_probe_s` (~3 min): the builder's own watch data
  shows hangs are long and recoveries happen between them, so a hung
  probe is killed at the cap and the loop keeps probing — many short
  probes catch a mid-window recovery that one budget-long hang never
  can. Killing a grant-holding client CAN wedge the pool, but a wedged
  probe guarantees a wasted window; the cap trades a possible wedge for
  a certain one. `max_probe_s=None` restores the wait-out behavior.
- optionally seed from `tpu_recovery_watch`'s last-known-healthy state
  (`state_path`): when the pool was healthy within `state_fresh_s`, the
  backoff between probes shrinks 3x — recoveries cluster, so probe
  eagerly right after known health.

Every attempt (offset, duration, outcome) is recorded via
`Attempt.record()` — the structured `bringup_probes` shape — and returned
so the emitted JSON itself shows whether the pool was down the whole
window. jax is imported lazily: importing this module must not touch the
backend.
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional, Tuple

from .policy import Deadline, RetryPolicy


def _read_state_age_s(state_path: Optional[str]) -> Optional[float]:
    """Age in seconds of the watch script's last-known-healthy marker:
    the file body is an epoch timestamp (one float/int line); a
    non-numeric body falls back to the file's mtime. None when absent."""
    if not state_path or not os.path.exists(state_path):
        return None
    try:
        with open(state_path) as fh:
            body = fh.read().strip().split()[0]
        ts = float(body)
    except (OSError, ValueError, IndexError):
        try:
            ts = os.path.getmtime(state_path)
        except OSError:
            return None
    return max(0.0, time.time() - ts)


def _run_probe_thread(probe_fn: Callable[[], str], deadline: Deadline,
                      max_probe_s: Optional[float]
                      ) -> Tuple[bool, int, str, str]:
    """Run an in-process probe callable on a worker thread so a hang can
    be observed and abandoned (the thread is a daemon; an abandoned probe
    dies with the process). Returns (hung, returncode, out, err)."""
    import threading
    res = {"out": "", "err": None}

    def _runner():
        try:
            res["out"] = str(probe_fn())
        except BaseException as e:  # noqa: BLE001 - surfaced as probe error
            res["err"] = f"{type(e).__name__}: {e}"

    th = threading.Thread(target=_runner, daemon=True)
    a0 = time.time()
    th.start()
    while th.is_alive() and not deadline.expired and (
            max_probe_s is None or time.time() - a0 < max_probe_s):
        th.join(0.05)
    if th.is_alive():
        return True, 1, "", ""
    if res["err"] is not None:
        return False, 1, "", res["err"]
    return False, 0, res["out"], ""


def backend_bringup(probe_code: str, budget_s: float = 1320.0,
                    retry_sleep_s: float = 90.0, min_probe_s: float = 60.0,
                    max_probe_s: Optional[float] = 180.0,
                    log: Optional[List] = None,
                    on_parent_hang: Optional[Callable[[], None]] = None,
                    probe_fn: Optional[Callable[[], str]] = None,
                    state_path: Optional[str] = None,
                    state_fresh_s: float = 900.0,
                    blacklist_after_hangs: Optional[int] = None
                    ) -> Tuple[object, list, Optional[str], List[dict]]:
    """Probe the backend until healthy or the budget ends, capping each
    probe at `max_probe_s` so one hang cannot eat the window.

    probe_code: python -c body that prints "... <platform>" on success.
    probe_fn: optional in-process probe callable returning that same
    output string (unit tests route a seeded FaultInjector-wrapped probe
    here to simulate init hangs without touching a pool); when given,
    probe_code is unused.
    log: optional list that receives attempt records as they happen (so a
    crash handler can still report the history).
    on_parent_hang: invoked if the parent's own backend init hangs after a
    healthy probe (default: hard-exit — the process is unrecoverable).
    state_path: optional last-known-healthy marker written by
    scripts/tpu_recovery_watch.sh; a fresh marker (< state_fresh_s old)
    shrinks the inter-probe backoff 3x.
    blacklist_after_hangs: ROADMAP item 4's compile-budget guard — a
    backend whose init/compile hangs this many times in one window is
    PATHOLOGICAL (wedged grant, runaway compile), not merely busy: the
    hung probe is killed as usual and the backend is then BLACKLISTED for
    the rest of the window (no further probes; immediate CPU fallback
    with a 'blacklisted' record), instead of feeding it the remaining
    budget one capped probe at a time. None (default) keeps probing —
    hangs and recoveries interleave on the shared pool, so the bar is
    opt-in per caller (bench.py sets it from BENCH_BLACKLIST_AFTER_HANGS).
    Returns (jax, devices, error_or_None, attempts).
    """
    import subprocess
    import sys
    import tempfile
    attempts: List[dict] = log if log is not None else []
    deadline = Deadline.after(budget_s)
    t0 = time.time()
    age = _read_state_age_s(state_path)
    if age is not None and age < state_fresh_s:
        retry_sleep_s = max(1.0, retry_sleep_s / 3.0)
        attempts.append({"t_s": 0.0, "dur_s": 0.0,
                         "outcome": f"seed: pool healthy {round(age)}s ago "
                                    f"— eager probing "
                                    f"(sleep {retry_sleep_s:.0f}s)"})
    policy = RetryPolicy(attempts=None, backoff_s=retry_sleep_s,
                         multiplier=1.0, jitter=0.1,
                         max_backoff_s=retry_sleep_s * 1.2)
    hang_kills = 0
    blacklisted = False
    # min_attempt_s: don't spawn a probe that can't get a fair shot — a
    # probe killed seconds into init is both useless and (if the pool is in
    # hang mode) a fresh grant-holding kill
    for a in policy.attempts_iter(deadline=deadline,
                                  min_attempt_s=min_probe_s):
        a0 = time.time()
        if probe_fn is not None:
            hung, rc, out, err = _run_probe_thread(probe_fn, deadline,
                                                   max_probe_s)
        else:
            # temp files, not PIPEs: a verbose plugin init can overflow a
            # 64 KB pipe buffer and block the child — indistinguishable
            # from an init hang from out here
            fo = tempfile.TemporaryFile(mode="w+")
            fe = tempfile.TemporaryFile(mode="w+")
            try:
                p = subprocess.Popen([sys.executable, "-c", probe_code],
                                     stdout=fo, stderr=fe, text=True)
            except OSError as e:
                # transient (EAGAIN under memory pressure, etc.) — retry
                # within the budget like any other failed attempt
                attempts.append(a.record(f"spawn failed: {e}"))
                fo.close()
                fe.close()
                continue
            while p.poll() is None and not deadline.expired and (
                    max_probe_s is None or time.time() - a0 < max_probe_s):
                time.sleep(0.5)
            hung = p.poll() is None
            if hung:
                p.kill()
                p.wait()
            fo.seek(0)
            out = fo.read()
            fe.seek(0)
            err = fe.read()
            fo.close()
            fe.close()
            rc = 1 if hung else p.returncode
        dur = time.time() - a0
        if hung:
            if deadline.expired:
                attempts.append(a.record("init hang — killed at budget end",
                                         dur))
                break
            # probe cap (round-5 verdict #1): kill the hung probe and KEEP
            # LOOPING — the next attempt may land in a recovery window
            attempts.append(a.record(
                f"init hang — killed at probe cap ({round(dur)}s)", dur))
            hang_kills += 1
            if blacklist_after_hangs is not None \
                    and blacklist_after_hangs > 0 \
                    and hang_kills >= blacklist_after_hangs:
                # pathologically-compiling/wedged backend: killed for the
                # last time and barred for the rest of this window — the
                # remaining budget goes to the caller (CPU fallback), not
                # to more doomed probes
                blacklisted = True
                attempts.append(a.record(
                    f"blacklisted: {hang_kills} init hangs in "
                    f"{round(time.time() - t0)}s — backend barred for "
                    f"the rest of the window"))
                break
            continue
        platform = out.strip().rsplit(" ", 1)[-1] if out.strip() else "?"
        if rc == 0 and platform not in ("cpu", "?"):
            attempts.append(a.record(f"healthy: {out.strip()}", dur))
            # The parent's OWN backend init can still hang (the probe's exit
            # released its grant; another client may grab or wedge the pool
            # in the gap). A watchdog guarantees the caller's mandatory
            # reporting still lands — the timer absorbs all remaining
            # bring-up budget (+ grace) first, so the hard-exit — itself a
            # grant-holding kill — fires only once waiting longer could no
            # longer produce a run anyway.
            import threading
            wd_s = max(240.0, deadline.remaining() + 120.0)
            hang_cb = on_parent_hang or (lambda: os._exit(1))
            watchdog = threading.Timer(wd_s, hang_cb)
            watchdog.daemon = True
            watchdog.start()
            try:
                import jax
                jdevs = jax.devices()
            except Exception as e:  # noqa: BLE001 - treat as failed attempt
                watchdog.cancel()
                attempts.append({"t_s": round(time.time() - t0, 1),
                                 "dur_s": 0.0,
                                 "outcome": f"parent init error: {e}"[:240]})
                break  # jax is imported now; can't retry backend selection
            watchdog.cancel()
            _publish_window(attempts, True, time.time() - t0)
            return jax, jdevs, None, list(attempts)
        detail = (err or out).strip().replace("\n", " ")[-220:]
        attempts.append(a.record(f"error: {detail}", dur))
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        # works even when jax was already imported by a failed parent-init
        # attempt above (the documented post-import CPU-forcing path)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    n_probes = sum(1 for a in attempts
                   if not a["outcome"].startswith(("parent", "healthy",
                                                   "seed", "blacklisted")))
    err_msg = (f"no healthy TPU across {n_probes} probe(s) in a "
               f"{round(time.time() - t0)} s bring-up window"
               + (f" (backend blacklisted after {hang_kills} init hangs)"
                  if blacklisted else "")
               + (" (a probe succeeded but the parent's own init failed)"
                  if n_probes != sum(1 for a in attempts
                                     if not a["outcome"].startswith(
                                         ("seed", "blacklisted")))
                  else ""))
    try:
        devs = jax.devices()
    except Exception as e:  # noqa: BLE001 - even CPU fallback can fail when
        # a poisoned backend cache survives the config update; surface it
        # with the probe history rather than crashing before any JSON lands
        raise RuntimeError(f"CPU fallback init failed after bring-up "
                           f"({err_msg}): {e}") from e
    _publish_window(attempts, False, time.time() - t0)
    return jax, devs, err_msg, list(attempts)


def _publish_window(attempts: List[dict], healthy: bool,
                    window_s: float) -> None:
    """Bring-up summary gauges into the telemetry registry (per-attempt
    counters already landed via Attempt.record); import inside the guard —
    bring-up must complete even with the observability layer broken."""
    try:
        from ..observability import publish_bringup
        publish_bringup(attempts, healthy, window_s)
    except Exception:  # noqa: BLE001 - telemetry never fails bring-up
        pass
