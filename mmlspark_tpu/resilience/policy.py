"""The ONE retry/backoff/deadline implementation in the codebase.

Reference: FaultToleranceUtils.retryWithTimeout (ModelDownloader.scala:37-52),
HandlingUtils.sendWithRetries (HTTPClients.scala:74-110, backoff array + 429
Retry-After), and the port-probe / rendezvous retry loops
(PortForwarding.scala:50-66, TrainUtils.scala:496-512). The port scattered
those into three incompatible ad-hoc loops (io/http.py, models/deep/
downloader.py, io/port_forwarding.py) plus bench.py's bring-up loop; all of
them now route through `RetryPolicy`, and `tests/test_resilience.py` lints
that no other module grows its own backoff loop again.

Two consumption styles:

- `policy.call(fn)` — exception-driven: run `fn` under a per-attempt hard
  timeout, retry retryable failures with jittered exponential backoff,
  bounded by an overall `Deadline`. Raises `RetryError` on exhaustion.
- `for attempt in policy.attempts_iter():` — loop-driven, for callers whose
  "failure" is a value (an HTTP 429/5xx response, a port already bound):
  the generator owns ALL sleeping between iterations; the caller breaks on
  success. `attempt.override_sleep_s` lets one iteration replace the
  policy's backoff (e.g. honoring a server's Retry-After).

`Deadline` is the request-budget object threaded through serving dispatch
and gateway forwarding: each hop re-encodes the REMAINING budget into the
`X-Deadline-Ms` header, so a request's budget shrinks across hops and an
expired request is answered 504 instead of occupying batch slots.
"""

from __future__ import annotations

import concurrent.futures
import math
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple


class RetryError(RuntimeError):
    """All attempts failed. `last` carries the final failure."""

    def __init__(self, attempts: int, last: Optional[BaseException]):
        super().__init__(f"all {attempts} attempts failed: {last}")
        self.attempts = attempts
        self.last = last


class DeadlineExceeded(RetryError):
    """The overall deadline expired before the attempts were exhausted."""

    def __init__(self, attempts_made: int, last: Optional[BaseException]):
        RuntimeError.__init__(
            self, f"deadline exceeded after {attempts_made} attempt(s): "
                  f"{last}")
        self.attempts = attempts_made
        self.last = last


class Deadline:
    """Monotonic-clock request budget, propagated across hops via header.

    `Deadline.after(1.5)` gives a hop 1.5 s; `to_header()` encodes whatever
    REMAINS at encode time, so forwarding a request re-budgets the next hop
    with only the unspent portion.
    """

    HEADER = "X-Deadline-Ms"

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float):
        self.expires_at = expires_at

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    @classmethod
    def never(cls) -> "Deadline":
        return cls(math.inf)

    def remaining(self) -> float:
        return max(0.0, self.expires_at - time.monotonic())

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at

    def to_header(self) -> str:
        return str(int(self.remaining() * 1000))

    @classmethod
    def from_headers(cls, headers: Optional[Dict[str, str]]
                     ) -> Optional["Deadline"]:
        """Case-insensitive `X-Deadline-Ms` lookup; None when absent or
        malformed (an unparseable budget must not kill the request)."""
        if not headers:
            return None
        for k, v in headers.items():
            if k.lower() == cls.HEADER.lower():
                try:
                    return cls.after(float(v) / 1000.0)
                except (TypeError, ValueError):
                    return None
        return None

    def __repr__(self):
        return f"Deadline(remaining={self.remaining():.3f}s)"


def parse_retry_after(value: Optional[str]) -> Optional[float]:
    """Seconds to wait from a Retry-After header value — both RFC 7231
    forms: delta-seconds ("120") and HTTP-date ("Wed, 21 Oct 2015 07:28:00
    GMT"). None when absent or unparseable (callers fall back to their
    backoff schedule)."""
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        pass
    from email.utils import parsedate_to_datetime
    from datetime import datetime, timezone
    try:
        dt = parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return max(0.0, (dt - datetime.now(timezone.utc)).total_seconds())


class Attempt:
    """One iteration of `RetryPolicy.attempts()`.

    `index` doubles as the probe offset for callers that map attempts onto
    a search space (port probing). `record()` emits the structured probe
    dict used by bench bring-up logs (`bringup_probes` shape)."""

    __slots__ = ("index", "t_s", "is_last", "override_sleep_s")

    def __init__(self, index: int, t_s: float, is_last: bool):
        self.index = index
        self.t_s = t_s
        self.is_last = is_last
        self.override_sleep_s: Optional[float] = None

    def record(self, outcome: str, dur_s: float = 0.0) -> Dict:
        # every structured probe record also lands in the telemetry
        # registry (bounded outcome-category label), so bring-up health is
        # scrapeable alongside serving/fit metrics; the import itself is
        # inside the guard — telemetry (including a broken or mid-shutdown
        # observability import) must never be a reason a retry loop can't
        # record its probe
        try:
            from ..observability import publish_probe_outcome
            publish_probe_outcome(outcome)
        except Exception:  # noqa: BLE001 - telemetry never fails a probe
            pass
        return {"t_s": round(self.t_s, 1), "dur_s": round(dur_s, 1),
                "outcome": outcome}


def _always_retry(e: BaseException) -> bool:
    return True


@dataclass(frozen=True)
class RetryPolicy:
    """attempts + backoff + jitter + per-attempt timeout + overall deadline
    + retryable predicate, in one immutable, reusable value.

    attempts=None means unbounded — only meaningful with a deadline (the
    bring-up probe loop's "retry until the wall budget" mode).
    schedule_s pins an explicit per-gap schedule (the reference's
    HTTPClients backoff array) instead of exponential growth.
    seed makes jitter deterministic (chaos tests; reproducible schedules).
    """

    attempts: Optional[int] = 3
    backoff_s: float = 0.5
    multiplier: float = 2.0
    max_backoff_s: float = 30.0
    jitter: float = 0.1
    timeout_s: Optional[float] = None
    deadline_s: Optional[float] = None
    retryable: Callable[[BaseException], bool] = field(default=_always_retry)
    schedule_s: Optional[Tuple[float, ...]] = None
    seed: Optional[int] = None

    @classmethod
    def from_backoffs_ms(cls, backoffs_ms: Sequence[float],
                         **kw) -> "RetryPolicy":
        """The reference's retry-array form (HTTPClients.scala:74-110):
        len(backoffs)+1 attempts with exactly those gaps, no jitter."""
        sched = tuple(b / 1000.0 for b in backoffs_ms)
        return cls(attempts=len(sched) + 1, schedule_s=sched, jitter=0.0,
                   **kw)

    # ------------------------------------------------------------- schedule
    def sleep_for(self, gap_index: int,
                  rng: Optional[random.Random] = None) -> float:
        """Jittered sleep after attempt `gap_index` (0-based gap)."""
        if self.schedule_s is not None:
            base = self.schedule_s[min(gap_index, len(self.schedule_s) - 1)]
        else:
            base = min(self.backoff_s * (self.multiplier ** gap_index),
                       self.max_backoff_s)
        if self.jitter and base > 0:
            r = rng if rng is not None else random
            base *= 1.0 + self.jitter * (2.0 * r.random() - 1.0)
        return max(0.0, base)

    def backoff_schedule(self, n: int) -> List[float]:
        """The first n sleeps this policy would take — deterministic when
        seeded (same seed => same schedule)."""
        rng = random.Random(self.seed) if self.seed is not None else None
        return [self.sleep_for(i, rng) for i in range(n)]

    # -------------------------------------------------------------- looping
    def attempts_iter(self, deadline: Optional[Deadline] = None,
                      min_attempt_s: float = 0.0) -> Iterator[Attempt]:
        """Yield attempts, sleeping the backoff between them. Stops when
        attempts are exhausted or the deadline cannot fit another sleep plus
        `min_attempt_s` of useful work (a probe spawned only to be killed is
        worse than no probe — it can wedge a shared device pool)."""
        if deadline is None and self.deadline_s is not None:
            deadline = Deadline.after(self.deadline_s)
        if self.attempts is None and deadline is None:
            raise ValueError(
                "RetryPolicy with attempts=None (unbounded) requires a "
                "deadline — otherwise a persistently failing callee retries "
                "forever")
        rng = random.Random(self.seed) if self.seed is not None else None
        t0 = time.monotonic()
        k = 0
        while True:
            is_last = self.attempts is not None and k == self.attempts - 1
            a = Attempt(k, time.monotonic() - t0, is_last)
            yield a
            k += 1
            if self.attempts is not None and k >= self.attempts:
                return
            sleep = (a.override_sleep_s if a.override_sleep_s is not None
                     else self.sleep_for(k - 1, rng))
            if deadline is not None and \
                    deadline.remaining() <= sleep + min_attempt_s:
                return
            if sleep > 0:
                time.sleep(sleep)

    # -------------------------------------------------------------- calling
    def call(self, fn: Callable, *args,
             deadline: Optional[Deadline] = None, **kw):
        """Run fn with per-attempt hard timeout + bounded retries.

        The hard timeout uses one throwaway single-worker executor per
        attempt, abandoned without joining: a `with` block
        (shutdown(wait=True)) would block on a hung fn and defeat the hard
        timeout this exists to provide (FaultToleranceUtils.retryWithTimeout,
        ModelDownloader.scala:37-52). The leaked worker thread dies with the
        hung call; cancel() is a no-op on a running future by design.
        """
        if deadline is None and self.deadline_s is not None:
            deadline = Deadline.after(self.deadline_s)
        last: Optional[BaseException] = None
        made = 0
        for a in self.attempts_iter(deadline=deadline):
            made += 1
            timeout = self.timeout_s
            if deadline is not None:
                rem = deadline.remaining()
                if rem <= 0:
                    raise DeadlineExceeded(made - 1, last)
                timeout = rem if timeout is None else min(timeout, rem)
            if timeout is None:
                try:
                    return fn(*args, **kw)
                except Exception as e:  # noqa: BLE001 - classified below
                    last = e
            else:
                ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
                fut = ex.submit(fn, *args, **kw)
                try:
                    result = fut.result(timeout=timeout)
                    ex.shutdown(wait=False)
                    return result
                except concurrent.futures.TimeoutError:
                    last = TimeoutError(f"attempt {a.index + 1} exceeded "
                                        f"{timeout}s")
                    fut.cancel()
                    ex.shutdown(wait=False)
                except Exception as e:  # noqa: BLE001 - classified below
                    last = e
                    ex.shutdown(wait=False)
            if not self.retryable(last):
                raise last
            if deadline is not None and deadline.expired:
                raise DeadlineExceeded(made, last)
        if self.attempts is not None and made >= self.attempts:
            raise RetryError(self.attempts, last)
        raise DeadlineExceeded(made, last)
