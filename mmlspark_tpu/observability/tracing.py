"""Request tracing: X-Trace-Id propagation + bounded structured event log.

A trace id is minted at the first hop that sees a request (the gateway,
or a worker hit directly) unless the client already sent `X-Trace-Id`;
every forward, retry, and failover hop re-sends the same id, and every
reply carries it back. Each hop appends per-span events (queue wait,
batch assembly, device dispatch, reply at workers; per-attempt forward
outcomes at the gateway) to its own `EventLog` — a bounded in-memory
ring with an optional JSONL file sink — so a slow request can be
explained hop by hop: grep both logs for the id and read the spans.

Events are plain dicts: {"ts": epoch-seconds, "trace_id", "span",
"dur_s", ...extras}. The ring bound makes the hot path allocation-cheap
and the memory ceiling fixed; the file sink is debug-grade (every event,
line-buffered) and off by default.
"""

from __future__ import annotations

import collections
import json
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

__all__ = ["TRACE_HEADER", "mint_trace_id", "trace_id_from_headers",
           "EventLog"]

TRACE_HEADER = "X-Trace-Id"


def mint_trace_id() -> str:
    """32-hex-char process-unique trace id."""
    return uuid.uuid4().hex


def trace_id_from_headers(headers: Optional[Dict[str, str]]
                          ) -> Optional[str]:
    """Case-insensitive `X-Trace-Id` lookup; None when absent or blank
    (a malformed id must not kill the request — a fresh one is minted)."""
    if not headers:
        return None
    for k, v in headers.items():
        if k.lower() == TRACE_HEADER.lower():
            v = str(v).strip()
            return v or None
    return None


class EventLog:
    """Bounded structured event ring + optional JSONL file sink.

    `append(span, trace_id, dur_s, **extra)` stamps the wall clock and
    records one event; the deque bound evicts the oldest, so a long-lived
    server holds at most `capacity` events no matter the traffic. The
    sink (when set) receives every event as one JSON line — including
    those later evicted from the ring.
    """

    def __init__(self, capacity: int = 4096,
                 sink_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._ring: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._sink = open(sink_path, "a", buffering=1) if sink_path else None

    def append(self, span: str, trace_id: Optional[str] = None,
               dur_s: Optional[float] = None, **extra: Any) -> None:
        ev: Dict[str, Any] = {"ts": round(time.time(), 6), "span": span}
        if trace_id is not None:
            ev["trace_id"] = trace_id
        if dur_s is not None:
            ev["dur_s"] = round(dur_s, 6)
        ev.update(extra)
        with self._lock:
            self._ring.append(ev)
            if self._sink is not None:
                try:
                    self._sink.write(json.dumps(ev) + "\n")
                except (OSError, ValueError):
                    # a torn-off sink (disk full, closed fd) must not take
                    # the dispatcher down; the ring still has the event
                    self._sink = None

    def events(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """Snapshot of ring events, oldest first; filtered to one trace
        when `trace_id` is given."""
        with self._lock:
            evs = list(self._ring)
        if trace_id is None:
            return evs
        return [e for e in evs if e.get("trace_id") == trace_id]

    def spans(self, trace_id: str) -> List[str]:
        """The span names recorded for one trace, in arrival order."""
        return [e["span"] for e in self.events(trace_id)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                finally:
                    self._sink = None
