"""Request tracing: X-Trace-Id propagation + bounded structured event log.

A trace id is minted at the first hop that sees a request (the gateway,
or a worker hit directly) unless the client already sent `X-Trace-Id`;
every forward, retry, and failover hop re-sends the same id, and every
reply carries it back. Each hop appends per-span events (queue wait,
batch assembly, device dispatch, reply at workers; per-attempt forward
outcomes at the gateway) to its own `EventLog` — a bounded in-memory
ring with an optional JSONL file sink — so a slow request can be
explained hop by hop: grep both logs for the id and read the spans.

Events are plain dicts: {"ts": epoch-seconds, "trace_id", "span",
"dur_s", ...extras}. The ring bound makes the hot path allocation-cheap
and the memory ceiling fixed; the file sink is debug-grade (every event,
line-buffered) and off by default.
"""

from __future__ import annotations

import collections
import json
import threading
import time
import uuid
import warnings
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["TRACE_HEADER", "mint_trace_id", "trace_id_from_headers",
           "EventLog", "drain_payload"]

TRACE_HEADER = "X-Trace-Id"


def mint_trace_id() -> str:
    """32-hex-char process-unique trace id."""
    return uuid.uuid4().hex


def trace_id_from_headers(headers: Optional[Dict[str, str]]
                          ) -> Optional[str]:
    """Case-insensitive `X-Trace-Id` lookup; None when absent or blank
    (a malformed id must not kill the request — a fresh one is minted)."""
    if not headers:
        return None
    for k, v in headers.items():
        if k.lower() == TRACE_HEADER.lower():
            v = str(v).strip()
            return v or None
    return None


def drain_payload(source: str, log: "EventLog",
                  since: float) -> Dict[str, Any]:
    """THE `GET /trace?since=` response body (one definition — the worker
    and gateway endpoints must never drift apart): the ring drained from
    the cursor, the source label, the next cursor (`now`), and the
    monotonic append count. `now` comes from the ATOMIC drain — it is
    the newest appended ts at the moment the events were read (or the
    request's own cursor when nothing is newer), so an append racing the
    drain can never land at ts <= now without being in `events`."""
    events, cursor = log.drain(since)
    return {"source": source,
            "now": cursor,
            "total_appended": log.total_appended,
            "events": events}


class EventLog:
    """Bounded structured event ring + optional JSONL file sink.

    `append(span, trace_id, dur_s, **extra)` stamps the wall clock and
    records one event; the deque bound evicts the oldest, so a long-lived
    server holds at most `capacity` events no matter the traffic. The
    sink (when set) receives every event as one JSON line — including
    those later evicted from the ring.
    """

    def __init__(self, capacity: int = 4096,
                 sink_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._ring: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._sink = open(sink_path, "a", buffering=1) if sink_path else None
        self._sink_path = sink_path
        self._appended = 0
        self._last_ts = 0.0

    def append(self, span: str, trace_id: Optional[str] = None,
               dur_s: Optional[float] = None, **extra: Any) -> None:
        ev: Dict[str, Any] = {"span": span}
        if trace_id is not None:
            ev["trace_id"] = trace_id
        if dur_s is not None:
            ev["dur_s"] = round(dur_s, 6)
        ev.update(extra)
        sink_err: Optional[Exception] = None
        with self._lock:
            # per-log STRICTLY increasing ts: two appends inside one
            # rounded microsecond (or a backward wall-clock step) must
            # not produce a ts <= an already-drained cursor — the
            # `/trace?since=` drain is strictly-greater, so a tie would
            # silently drop the event from every future drain
            ts = round(time.time(), 6)
            if ts <= self._last_ts:
                ts = round(self._last_ts + 1e-6, 6)
            self._last_ts = ts
            ev["ts"] = ts
            self._ring.append(ev)
            self._appended += 1
            if self._sink is not None:
                try:
                    self._sink.write(json.dumps(ev) + "\n")
                except (OSError, ValueError) as e:
                    # a torn-off sink (disk full, closed fd) must not take
                    # the dispatcher down; the ring still has the event.
                    # CLOSE the file object (the fd would otherwise leak
                    # for the process lifetime) and signal below — a
                    # silently dropped sink is how trace forensics go
                    # missing exactly when they are needed
                    sink_err = e
                    try:
                        self._sink.close()
                    except Exception:  # noqa: BLE001 - already broken
                        pass
                    self._sink = None
        if sink_err is not None:
            self._record_sink_error(sink_err)

    def _record_sink_error(self, err: Exception) -> None:
        """One warning + a counted `tracing_sink_errors_total` so a dead
        JSONL sink is visible in the scrape, not a silent None."""
        warnings.warn(
            f"EventLog JSONL sink {self._sink_path!r} torn off and closed "
            f"({type(err).__name__}: {err}); ring buffering continues",
            stacklevel=3)
        try:
            from .metrics import get_registry
            get_registry().counter(
                "tracing_sink_errors_total",
                "EventLog JSONL sinks torn off by a write error").inc()
        except Exception:  # noqa: BLE001 - telemetry must not kill tracing
            pass

    @property
    def total_appended(self) -> int:
        """Monotonic count of events ever appended (ring evictions
        included) — piggybacked on worker heartbeats so the collector can
        tell 'quiet ring' from 'ring overflowed between drains'."""
        with self._lock:
            return self._appended

    def events(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """Snapshot of ring events, oldest first; filtered to one trace
        when `trace_id` is given."""
        with self._lock:
            evs = list(self._ring)
        if trace_id is None:
            return evs
        return [e for e in evs if e.get("trace_id") == trace_id]

    def events_since(self, since: float) -> List[Dict[str, Any]]:
        """Ring events with ts STRICTLY greater than `since`, oldest
        first — the `GET /trace?since=` drain contract: a poller passes
        the `now` of its previous drain and receives only new events."""
        with self._lock:
            return [e for e in self._ring if e["ts"] > since]

    def drain(self, since: float) -> "Tuple[List[Dict[str, Any]], float]":
        """(events newer than `since`, next cursor) ATOMICALLY: the
        cursor is the newest appended ts as of the read (ts stamping and
        this read share the ring lock), so no event can exist with
        ts <= cursor that the drain did not return — the race a
        separately-computed wall-clock 'now' would lose."""
        with self._lock:
            return ([e for e in self._ring if e["ts"] > since],
                    max(since, self._last_ts))

    def spans(self, trace_id: str) -> List[str]:
        """The span names recorded for one trace, in arrival order."""
        return [e["span"] for e in self.events(trace_id)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                finally:
                    self._sink = None
