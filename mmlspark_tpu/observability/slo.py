"""SLO burn-rate monitors: fast/slow dual windows over registry families.

The registry (PR 8) already carries every error counter and latency
histogram; what operators lack is the DERIVATIVE — "are we spending the
error budget faster than the SLO allows, right now and sustained?". This
module computes the classic multi-window burn rate (Google SRE workbook
ch. 5) from periodic registry samples:

    burn = (bad / total over the window) / error_budget        (error SLO)
    burn = windowed p99 / objective                            (latency SLO)

over a FAST window (seconds-to-minutes: catches a cliff) and a SLOW
window (minutes-to-hours: filters blips). A breach requires BOTH windows
burning past `breach_burn_rate` — the fast window alone is one bad batch,
the slow window alone is stale history. Burn rates surface as
`slo_burn_rate{slo,window}` gauges, transitions as structured `slo`
events in the monitor's EventLog (which the TraceCollector drains and
the flight recorder dumps), and the coordinator exposes the whole status
block in `/health` and can (off by default) gate rollouts on it.

Counters are CUMULATIVE, so windowed rates come from a ring of (ts,
value) samples; the latency window comes from diffing the histogram's
cumulative bucket counts between two samples — an exact windowed
distribution, not an approximation over the process lifetime. Clock and
sampling are injectable: tests drive error-rate across the fast window
threshold with zero sleeps.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .metrics import MetricsRegistry, get_registry
from .tracing import EventLog, mint_trace_id

__all__ = ["SLODef", "SLOMonitor", "windowed_quantile"]


def _family_totals(snapshot: Dict[str, Any], family: str) -> float:
    fam = snapshot.get(family)
    if not fam:
        return 0.0
    return float(sum(s.get("value", 0.0) for s in fam["series"]))


def _family_buckets(snapshot: Dict[str, Any], family: str
                    ) -> Tuple[Dict[str, float], int]:
    """Summed per-bucket counts + total count of one histogram family
    across all label sets (bucket keys are the bound reprs + '+Inf')."""
    fam = snapshot.get(family)
    agg: Dict[str, float] = {}
    count = 0
    if fam:
        for s in fam["series"]:
            count += int(s.get("count", 0))
            for k, v in (s.get("buckets") or {}).items():
                agg[k] = agg.get(k, 0.0) + v
    return agg, count


def windowed_quantile(old: Tuple[Dict[str, float], int],
                      new: Tuple[Dict[str, float], int],
                      q: float) -> Optional[float]:
    """q-quantile of the observations that landed BETWEEN two histogram
    samples, by diffing cumulative bucket counts. Returns the upper bound
    of the bucket holding the target rank (None when the window is
    empty); +Inf-bucket hits report the largest finite bound — a
    conservative floor, which is the right bias for a breach gate."""
    ob, oc = old
    nb, nc = new
    total = nc - oc
    if total <= 0:
        return None
    deltas = []
    for key, v in nb.items():
        d = v - ob.get(key, 0.0)
        bound = float("inf") if key == "+Inf" else float(key)
        deltas.append((bound, max(0.0, d)))
    deltas.sort(key=lambda kv: kv[0])
    rank = q * total
    cum = 0.0
    finite = [b for b, _ in deltas if b != float("inf")]
    for bound, d in deltas:
        cum += d
        if cum >= rank:
            if bound == float("inf"):
                return finite[-1] if finite else None
            return bound
    return finite[-1] if finite else None


class SLODef:
    """One service-level objective.

    kind "error_rate": `bad` counter families over `total` families (a
    histogram family's count works as a total), with `budget` = allowed
    bad fraction (0.01 = 99% objective). Burn 1.0 means spending exactly
    the budget; >1 is over-spend.

    kind "latency_p99": histogram `family` with `objective_ms`; burn =
    windowed p99 / objective.
    """

    KINDS = ("error_rate", "latency_p99")

    def __init__(self, name: str, kind: str,
                 bad: Sequence[str] = (), total: Sequence[str] = (),
                 budget: float = 0.01,
                 family: Optional[str] = None,
                 objective_ms: Optional[float] = None):
        if kind not in self.KINDS:
            raise ValueError(f"kind must be one of {self.KINDS}, "
                             f"got {kind!r}")
        if kind == "error_rate" and (not bad or not total):
            raise ValueError("error_rate SLO needs bad= and total= "
                             "family lists")
        if kind == "latency_p99" and (not family or not objective_ms):
            raise ValueError("latency_p99 SLO needs family= and "
                             "objective_ms=")
        self.name = name
        self.kind = kind
        self.bad = tuple(bad)
        self.total = tuple(total)
        self.budget = float(budget)
        self.family = family
        self.objective_ms = objective_ms


class _Sample:
    __slots__ = ("ts", "bad", "total", "hist")

    def __init__(self, ts, bad, total, hist):
        self.ts = ts
        self.bad = bad          # {slo_name: cumulative bad}
        self.total = total      # {slo_name: cumulative total}
        self.hist = hist        # {slo_name: (buckets, count)}


class SLOMonitor:
    """Samples the registry on `tick()` and maintains fast/slow burn
    rates per SLO. `status()` is the /health block; `breached()` is the
    rollout-gate predicate (fast AND slow both past `breach_burn_rate`).
    """

    WINDOWS = ("fast", "slow")

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 slos: Optional[Sequence[SLODef]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 fast_window_s: float = 60.0, slow_window_s: float = 600.0,
                 breach_burn_rate: float = 1.0,
                 event_log: Optional[EventLog] = None,
                 metrics_label: str = "slo"):
        if fast_window_s >= slow_window_s:
            raise ValueError("fast_window_s must be < slow_window_s")
        self.registry = registry if registry is not None else get_registry()
        self.slos: List[SLODef] = list(slos or ())
        self.clock = clock
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.breach_burn_rate = float(breach_burn_rate)
        self.events = event_log if event_log is not None else EventLog(256)
        self._lbl = {"instance": metrics_label}
        self._samples: List[_Sample] = []
        self._lock = threading.Lock()
        self._burn: Dict[Tuple[str, str], Optional[float]] = {}
        self._breached: Dict[str, bool] = {}
        self._gauges: Dict[Tuple[str, str], Any] = {}

    def _gauge(self, slo: str, window: str):
        g = self._gauges.get((slo, window))
        if g is None:
            g = self.registry.gauge(
                "slo_burn_rate",
                "error-budget burn rate per SLO and window (1.0 = "
                "spending exactly the budget)",
                {**self._lbl, "slo": slo, "window": window})
            self._gauges[(slo, window)] = g
        return g

    # -------------------------------------------------------------- sampling
    def _needed_families(self) -> List[str]:
        fams: List[str] = []
        for slo in self.slos:
            fams.extend(slo.bad)
            fams.extend(slo.total)
            if slo.family:
                fams.append(slo.family)
        return fams

    def _take_sample(self) -> _Sample:
        # per-family snapshot: a periodic sampler must not serialize the
        # WHOLE registry (every histogram's interpolated quantiles) under
        # its lock every tick just to read 1-3 families
        snap = self.registry.snapshot(families=self._needed_families())
        bad: Dict[str, float] = {}
        total: Dict[str, float] = {}
        hist: Dict[str, Tuple[Dict[str, float], int]] = {}
        for slo in self.slos:
            if slo.kind == "error_rate":
                bad[slo.name] = sum(_family_totals(snap, f)
                                    for f in slo.bad)
                t = 0.0
                for f in slo.total:
                    fam = snap.get(f)
                    if fam and fam["kind"] == "histogram":
                        t += sum(s.get("count", 0) for s in fam["series"])
                    else:
                        t += _family_totals(snap, f)
                total[slo.name] = t
            else:
                hist[slo.name] = _family_buckets(snap, slo.family)
        return _Sample(self.clock(), bad, total, hist)

    def _window_base(self, now: float, window_s: float
                     ) -> Optional[_Sample]:
        """Oldest retained sample inside the window (None = cannot form a
        window yet — burn unknown, reported as 0)."""
        base = None
        for s in self._samples:
            if now - s.ts <= window_s:
                base = s
                break
        return base

    def tick(self) -> Dict[str, Dict[str, Any]]:
        """One sampling + burn computation. Returns `status()`."""
        sample = self._take_sample()
        with self._lock:
            self._samples.append(sample)
            cutoff = sample.ts - self.slow_window_s * 1.25
            while self._samples and self._samples[0].ts < cutoff:
                self._samples.pop(0)
            now = sample.ts
            for slo in self.slos:
                burns = {}
                for window, wsec in (("fast", self.fast_window_s),
                                     ("slow", self.slow_window_s)):
                    base = self._window_base(now, wsec)
                    burn = None
                    # warm-up guard: until history actually SPANS (half
                    # of) a window, its burn is unknown — without it the
                    # fast and slow burns of a young monitor are computed
                    # over the same short span, and the slow window
                    # "filters" nothing: a 1-second blip at 2 s uptime
                    # would breach both windows and (with the gate on)
                    # roll a rollout back — exactly the transient the
                    # dual-window design exists to suppress
                    if base is not None and base is not sample \
                            and now - base.ts >= 0.5 * wsec:
                        if slo.kind == "error_rate":
                            dt_total = (sample.total[slo.name]
                                        - base.total.get(slo.name, 0.0))
                            dt_bad = (sample.bad[slo.name]
                                      - base.bad.get(slo.name, 0.0))
                            if dt_total > 0:
                                burn = (dt_bad / dt_total) / slo.budget
                        else:
                            p99 = windowed_quantile(
                                base.hist.get(slo.name, ({}, 0)),
                                sample.hist[slo.name], 0.99)
                            if p99 is not None:
                                burn = (p99 * 1e3) / slo.objective_ms
                    burns[window] = burn
                    self._burn[(slo.name, window)] = burn
                    self._gauge(slo.name, window).set(burn or 0.0)
                was = self._breached.get(slo.name, False)
                is_breached = all(
                    burns[w] is not None and burns[w] > self.breach_burn_rate
                    for w in self.WINDOWS)
                self._breached[slo.name] = is_breached
                if is_breached != was:
                    # structured transition event: drained by the
                    # TraceCollector, dumped by the flight recorder
                    self.events.append(
                        "slo", mint_trace_id(), slo=slo.name,
                        state="breach" if is_breached else "clear",
                        burn_fast=round(burns["fast"] or 0.0, 3),
                        burn_slow=round(burns["slow"] or 0.0, 3))
        return self.status()

    # --------------------------------------------------------------- queries
    def status(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out = {}
            for slo in self.slos:
                out[slo.name] = {
                    "kind": slo.kind,
                    "burn_fast": self._burn.get((slo.name, "fast")),
                    "burn_slow": self._burn.get((slo.name, "slow")),
                    "breached": self._breached.get(slo.name, False),
                }
            return out

    def breached(self) -> bool:
        """True when ANY SLO burns past threshold on BOTH windows — the
        (off-by-default) rollout-gate predicate."""
        with self._lock:
            return any(self._breached.values())

    # ------------------------------------------------------------- defaults
    @classmethod
    def gateway_defaults(cls, registry: MetricsRegistry,
                         availability_budget: float = 0.01,
                         p99_objective_ms: float = 250.0,
                         **kw) -> "SLOMonitor":
        """The coordinator's stock SLO pair: availability (shed + expired
        over all gateway replies) and latency (gateway p99 vs objective).
        Families are the ones the gateway already maintains — nothing new
        is measured."""
        slos = [
            SLODef("availability", "error_rate",
                   bad=("gateway_shed_total", "gateway_expired_total"),
                   total=("gateway_request_latency_seconds",),
                   budget=availability_budget),
            SLODef("latency", "latency_p99",
                   family="gateway_request_latency_seconds",
                   objective_ms=p99_objective_ms),
        ]
        return cls(registry=registry, slos=slos, **kw)
