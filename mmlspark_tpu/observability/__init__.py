"""Unified telemetry layer: metrics registry, /metrics export, tracing.

One queryable surface for everything the system measures about itself
(the reference's StopWatch-diagnostics-DataFrame role, grown into a
production telemetry plane):

- `MetricsRegistry` — thread-safe counters / gauges / fixed-bucket
  histograms (interpolated p50/p95/p99), labeled series, deterministic
  snapshot order, Prometheus-text rendering; `get_registry()` is the
  process-global default every component lands on.
- `EventLog` + `X-Trace-Id` propagation — per-hop structured spans
  (queue wait, batch assembly, device dispatch, reply; gateway forward
  attempts) in a bounded ring with an optional JSONL sink, so a slow
  request is explained hop by hop.
- the profiling bridge — StopWatch / FitTimeline / bring-up probe
  outcomes published into the registry, so fit-side and serving-side
  telemetry land in one scrape.
- the fleet plane (ISSUE 14) — `TraceCollector` drains every hop's
  EventLog over `GET /trace?since=` and assembles end-to-end trace
  trees; `FlightRecorder` dumps atomic incident bundles on anomaly
  triggers (swap rollback, shed spike, p99/SLO breach); `SLOMonitor`
  computes fast/slow-window error-budget burn rates surfaced in the
  coordinator's /health and as `slo_burn_rate{slo,window}` gauges.

Wired into `io/serving.py` (GET /metrics beside /health), the
`ServingCoordinator` gateway, `DistributedServingServer` workers,
`resilience/` (retry/shed/eviction/probe counters), the GBDT fit loop,
and bench.py (snapshot embedded in the bench JSON).
tests/test_observability.py lints that io/ and resilience/ grow no new
ad-hoc latency counters or hand-rolled stat dicts outside this layer.
"""

from .metrics import (Counter, DEFAULT_LATENCY_BUCKETS, Gauge, Histogram,
                      MetricsRegistry, get_registry, set_registry)
from .tracing import (EventLog, TRACE_HEADER, mint_trace_id,
                      trace_id_from_headers)
from .bridge import (classify_probe_outcome, publish_bringup,
                     publish_checkpoint_event, publish_fit_metrics,
                     publish_fit_timeline, publish_ingest_metrics,
                     publish_ingest_verify_failure, publish_multichip_fit,
                     publish_probe_outcome, publish_rendezvous_event,
                     publish_stopwatch, set_hosts_alive)
from .collector import REQUEST_SPANS, SYSTEM_SPANS, TraceCollector
from .flightrecorder import BUNDLE_SCHEMA_VERSION, FlightRecorder
from .slo import SLODef, SLOMonitor, windowed_quantile

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS", "get_registry", "set_registry",
    "EventLog", "TRACE_HEADER", "mint_trace_id", "trace_id_from_headers",
    "classify_probe_outcome", "publish_bringup", "publish_checkpoint_event",
    "publish_fit_metrics", "publish_fit_timeline", "publish_ingest_metrics",
    "publish_ingest_verify_failure", "publish_multichip_fit",
    "publish_probe_outcome", "publish_rendezvous_event", "publish_stopwatch",
    "set_hosts_alive",
    "TraceCollector", "REQUEST_SPANS", "SYSTEM_SPANS",
    "FlightRecorder", "BUNDLE_SCHEMA_VERSION",
    "SLODef", "SLOMonitor", "windowed_quantile",
]
