"""Bridge fit-side profiling artifacts into the metrics registry.

The fit path already measures itself — `StopWatch` phase decompositions,
the barrier-free `FitTimeline` (overlap_ratio, commit_wait), bring-up
probe records (`resilience/bringup.py`) — but until now those numbers
lived only on the fitted booster or inside BENCH_*.json. This module
publishes them as registry series so one `/metrics` scrape (or one
`snapshot()` embedded in bench JSON) carries fit-side AND serving-side
telemetry.

Publication is best-effort by design: a telemetry failure must never
fail a fit, so each publisher warns once instead of raising.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Optional

from .metrics import MetricsRegistry, get_registry

__all__ = ["publish_stopwatch", "publish_fit_timeline",
           "publish_fit_metrics", "publish_multichip_fit",
           "classify_probe_outcome", "publish_probe_outcome",
           "publish_bringup", "publish_checkpoint_event",
           "publish_rendezvous_event", "set_hosts_alive",
           "publish_vw_fused_decision", "publish_vw_step_metrics",
           "publish_ingest_metrics", "publish_ingest_verify_failure",
           "publish_online_event", "publish_online_refusal",
           "publish_online_apply", "publish_online_publish"]

#: bounded label vocabulary for rendezvous events — the raw error strings
#: carry addresses/counts that must not become label cardinality
_RENDEZVOUS_EVENTS = ("bind", "join", "wait", "heartbeat", "leave",
                      "initialize", "host")
_RENDEZVOUS_OUTCOMES = ("ok", "rejoin", "duplicate", "roster_full",
                        "bad_process_id", "timeout", "lost", "heal",
                        "unknown", "error", "port_in_use",
                        "no_jax_coordinator")


def publish_rendezvous_event(event: str, outcome: str = "ok",
                             registry: Optional[MetricsRegistry] = None
                             ) -> None:
    """One multi-host rendezvous/fabric event (parallel/rendezvous.py,
    parallel/multihost.py, mesh.distributed_init) -> bounded-label
    counter. A counted timeout is the contract: a missing host must be a
    scrapeable event, never a silent hang."""
    reg = registry or get_registry()
    try:
        reg.counter("multihost_rendezvous_events_total",
                    "multi-host rendezvous/fabric events by kind and "
                    "outcome",
                    labels={"event": event if event in _RENDEZVOUS_EVENTS
                            else "other",
                            "outcome": outcome if outcome in
                            _RENDEZVOUS_OUTCOMES else "other"}).inc()
    except Exception as e:  # noqa: BLE001 - telemetry must not fail rendezvous
        warnings.warn(f"publish_rendezvous_event failed: {e}", stacklevel=2)


def set_hosts_alive(n: int,
                    registry: Optional[MetricsRegistry] = None) -> None:
    """Coordinator-side liveness gauge: joined hosts currently beating
    (or never yet subject to eviction)."""
    reg = registry or get_registry()
    try:
        reg.gauge("multihost_hosts_alive",
                  "hosts joined to the rendezvous and not heartbeat-lost"
                  ).set(float(n))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail rendezvous
        warnings.warn(f"set_hosts_alive failed: {e}", stacklevel=2)

#: checkpoint save/restore durations span ~1 ms (tiny boosters) to tens of
#: seconds (orbax trees over NFS) — the serving-latency buckets top out
#: far too low for them
_CHECKPOINT_SECONDS_BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0,
                               30.0, 120.0)


def publish_checkpoint_event(event: str, outcome: str = "ok",
                             seconds: Optional[float] = None,
                             registry: Optional[MetricsRegistry] = None
                             ) -> None:
    """One elastic-recovery event (resilience/elastic.py + the fit resume
    paths) -> a bounded-label counter and, when timed, a duration
    histogram. Events: save / restore / fallback / resume / drain_signal /
    drain_complete / drain_grace_exceeded / gc; outcomes are bounded
    per-event categories (ok, none, digest_mismatch, reshard, ...)."""
    reg = registry or get_registry()
    try:
        reg.counter("checkpoint_events_total",
                    "elastic checkpoint/drain events by kind and outcome",
                    labels={"event": event, "outcome": outcome}).inc()
        if seconds is not None:
            reg.histogram("checkpoint_event_seconds",
                          "duration of timed elastic checkpoint events",
                          labels={"event": event},
                          buckets=_CHECKPOINT_SECONDS_BUCKETS
                          ).observe(float(seconds))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail recovery
        warnings.warn(f"publish_checkpoint_event failed: {e}", stacklevel=2)


def publish_stopwatch(summary: Dict[str, Any], prefix: str = "fit_phase",
                      registry: Optional[MetricsRegistry] = None) -> None:
    """StopWatch.summary() -> `<prefix>_seconds{phase=...}` gauges (the
    VW-TrainingStats diagnostics shape, now scrapeable)."""
    reg = registry or get_registry()
    try:
        for phase, slot in summary.items():
            if isinstance(slot, dict) and "total_s" in slot:
                reg.gauge(f"{prefix}_seconds",
                          "wall seconds per fit phase (last fit)",
                          labels={"phase": phase}).set(slot["total_s"])
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the fit
        warnings.warn(f"publish_stopwatch failed: {e}", stacklevel=2)


def publish_fit_timeline(summary: Dict[str, Any],
                         prefix: str = "fit_pipeline",
                         registry: Optional[MetricsRegistry] = None) -> None:
    """FitTimeline.summary() -> overlap/commit-wait/busy gauges."""
    reg = registry or get_registry()
    try:
        mapping = {"wall_s": "wall_seconds",
                   "host_busy_s": "host_busy_seconds",
                   "device_busy_s": "device_busy_seconds",
                   "wait_s": "commit_wait_seconds",
                   "overlap_ratio": "overlap_ratio"}
        for src, dst in mapping.items():
            if src in summary and summary[src] is not None:
                reg.gauge(f"{prefix}_{dst}",
                          "pipelined-fit timeline (last fit)"
                          ).set(float(summary[src]))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the fit
        warnings.warn(f"publish_fit_timeline failed: {e}", stacklevel=2)


#: per-block read->bin->dispatch spans: ~5 ms (small cached shards) to
#: tens of seconds (cold NFS reads of multi-GB blocks)
_INGEST_BLOCK_SECONDS_BUCKETS = (0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 30.0)


def publish_ingest_metrics(rows: int, seconds: float,
                           rss_bytes: Optional[int] = None,
                           block_seconds: Optional[list] = None,
                           registry: Optional[MetricsRegistry] = None
                           ) -> None:
    """One completed out-of-core ingest pass (io/shardstore
    stream_fit_arrays): headline rows/s gauge, per-block duration
    histogram, and the post-pass host RSS the bounded-memory contract
    (docs/DATA.md) is judged by."""
    reg = registry or get_registry()
    try:
        if seconds > 0:
            reg.gauge("ingest_rows_per_s",
                      "last out-of-core ingest throughput (rows/s, "
                      "read->bin->device_put)").set(rows / seconds)
        if rss_bytes is not None:
            reg.gauge("ingest_rss_bytes",
                      "host RSS sampled at the end of the last ingest "
                      "pass (the docs/DATA.md bounded-memory contract)"
                      ).set(float(rss_bytes))
        if block_seconds:
            h = reg.histogram("ingest_block_seconds",
                              "per-block read->bin->dispatch span of the "
                              "streaming ingest ring",
                              buckets=_INGEST_BLOCK_SECONDS_BUCKETS)
            for s in block_seconds:
                h.observe(float(s))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail ingest
        warnings.warn(f"publish_ingest_metrics failed: {e}", stacklevel=2)


def publish_ingest_verify_failure(
        registry: Optional[MetricsRegistry] = None) -> None:
    """One shard sha256 verification failure (ShardStore.verify): silent
    on-disk corruption must be a scrapeable event, never just a raised
    exception someone's retry loop swallows."""
    reg = registry or get_registry()
    try:
        reg.counter("ingest_verify_failures_total",
                    "shard sha256 mismatches found by ShardStore.verify"
                    ).inc()
    except Exception as e:  # noqa: BLE001 - telemetry must not fail verify
        warnings.warn(f"publish_ingest_verify_failure failed: {e}",
                      stacklevel=2)


def publish_fit_metrics(rows: int, iters: int, wall_s: float,
                        timings: Optional[Dict[str, Any]] = None,
                        registry: Optional[MetricsRegistry] = None) -> None:
    """The GBDT fit-loop hook: every completed fit lands a counter + the
    headline throughput gauge; a collectFitTimings fit additionally lands
    its phase decomposition and pipeline timeline."""
    reg = registry or get_registry()
    try:
        reg.counter("gbdt_fits_total", "completed booster fits").inc()
        reg.gauge("gbdt_fit_wall_seconds", "last fit wall time").set(wall_s)
        reg.gauge("gbdt_fit_rows", "rows in the last fit").set(rows)
        if wall_s > 0:
            reg.gauge("gbdt_fit_rows_iter_per_s",
                      "last-fit training throughput (rows*iters/s — the "
                      "bench headline unit)").set(rows * iters / wall_s)
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the fit
        warnings.warn(f"publish_fit_metrics failed: {e}", stacklevel=2)
        return
    if not timings:
        return
    publish_stopwatch({k: v for k, v in timings.items()
                       if isinstance(v, dict) and "total_s" in v},
                      registry=reg)
    tl = timings.get("timeline") or {}
    if isinstance(tl, dict) and isinstance(tl.get("construction"), dict):
        publish_fit_timeline(tl["construction"], registry=reg)


def publish_multichip_fit(decision, straggler_gap_s: Optional[float] = None,
                          allreduce_wall_s: Optional[float] = None,
                          registry: Optional[MetricsRegistry] = None) -> None:
    """The multi-chip fit hook: every strategy decision (even 'serial' on
    one device) lands as a bounded-label counter plus the comm-model
    gauges, so the /metrics scrape and the bench snapshot show WHICH
    learner ran, WHY (predicted voting advantage vs threshold), and what
    it costs per split. Straggler gap and measured allreduce wall arrive
    only from instrumented runs (collectFitTimings /
    scripts/measure_multichip_fit.py) — absent means not measured, not
    zero.

    `decision` is a parallel/strategy.StrategyDecision (the strategy set
    {serial, data_parallel, voting_parallel} x requested aliases is a
    bounded label space)."""
    reg = registry or get_registry()
    try:
        reg.counter("gbdt_fit_strategy_selected_total",
                    "fits per resolved tree-learner strategy",
                    labels={"strategy": decision.strategy,
                            "requested": decision.requested}).inc()
        reg.gauge("gbdt_fit_ndev",
                  "data-axis devices of the last fit (1 = serial)"
                  ).set(float(decision.ndev))
        reg.gauge("gbdt_fit_comm_bytes_per_split",
                  "closed-form allreduce payload bytes per split at the "
                  "last fit's shape", labels={"strategy": "data_parallel"}
                  ).set(float(decision.dp_bytes_per_split))
        reg.gauge("gbdt_fit_comm_bytes_per_split",
                  "closed-form allreduce payload bytes per split at the "
                  "last fit's shape", labels={"strategy": "voting_parallel"}
                  ).set(float(decision.voting_bytes_per_split))
        reg.gauge("gbdt_fit_voting_advantage",
                  "predicted dp/voting traffic ratio at the last fit's "
                  "shape (chooser threshold in "
                  "gbdt_fit_voting_threshold)").set(float(decision.advantage))
        reg.gauge("gbdt_fit_voting_threshold",
                  "auto-mode ratio above which voting_parallel is chosen"
                  ).set(float(decision.threshold))
        # fleet topology + DCN traffic (ISSUE 15): getattr-tolerant so a
        # pre-multihost decision tuple (older bench JSON replayed through
        # StrategyDecision) still publishes
        hosts = int(getattr(decision, "hosts", 1) or 1)
        reg.gauge("gbdt_fit_hosts",
                  "hosts (jax processes) in the last fit's mesh"
                  ).set(float(hosts))
        reg.gauge("gbdt_fit_devices_per_host",
                  "local devices per host in the last fit's mesh"
                  ).set(float(getattr(decision, "devices_per_host", 0) or 0))
        reg.gauge("gbdt_fit_comm_inter_host_bytes_per_split",
                  "closed-form DCN (cross-host) allreduce payload bytes "
                  "per split at the last fit's shape (0 = single host)",
                  labels={"strategy": "data_parallel"}).set(float(getattr(
                      decision, "dp_inter_host_bytes_per_split", 0)))
        reg.gauge("gbdt_fit_comm_inter_host_bytes_per_split",
                  "closed-form DCN (cross-host) allreduce payload bytes "
                  "per split at the last fit's shape (0 = single host)",
                  labels={"strategy": "voting_parallel"}).set(float(getattr(
                      decision, "voting_inter_host_bytes_per_split", 0)))
        if straggler_gap_s is not None:
            reg.gauge("gbdt_fit_shard_straggler_gap_seconds",
                      "slowest-minus-fastest shard transfer completion of "
                      "the last instrumented sharded fit"
                      ).set(float(straggler_gap_s))
        if allreduce_wall_s is not None:
            reg.gauge("gbdt_fit_allreduce_wall_seconds",
                      "measured wall of one child-slice allreduce over "
                      "the fit mesh (scripts/measure_multichip_fit.py)"
                      ).set(float(allreduce_wall_s))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the fit
        warnings.warn(f"publish_multichip_fit failed: {e}", stacklevel=2)


#: VW online steps span ~50 us (small minibatch, CPU dispatch-bound) to
#: seconds (first-step compile); the serving-latency buckets start too
#: high to resolve the hot band
_VW_STEP_SECONDS_BUCKETS = (1e-5, 5e-5, 2e-4, 1e-3, 5e-3, 0.02, 0.1,
                            0.5, 2.0, 10.0)
#: fusedTables modes — bounded label vocabulary
_VW_FUSED_MODES = ("auto", "on", "off")


def publish_vw_fused_decision(mode: str, fused: bool,
                              registry: Optional[MetricsRegistry] = None
                              ) -> None:
    """One fusedTables resolution (models/vw/base.py) -> bounded-label
    counter: WHICH mode was requested and WHAT the step actually ran
    (packed [R, 2^b] table vs per-table gather/scatter). The auto rule
    lives in sgd.resolve_auto_fused; this makes its decisions scrapeable
    so a fleet running the slow layout is visible, not folklore."""
    reg = registry or get_registry()
    try:
        reg.counter("vw_fused_tables_total",
                    "VW step-layout decisions by fusedTables mode and "
                    "resolved layout",
                    labels={"mode": mode if mode in _VW_FUSED_MODES
                            else "other",
                            "decision": "fused" if fused else "unpacked"}
                    ).inc()
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the fit
        warnings.warn(f"publish_vw_fused_decision failed: {e}", stacklevel=2)


def publish_vw_step_metrics(step_seconds: Optional[float] = None,
                            examples_per_s: Optional[float] = None,
                            registry: Optional[MetricsRegistry] = None
                            ) -> None:
    """VW online-ring telemetry at the metricsEvery cadence
    (models/vw/online.py): per-step dispatch->retire latency histogram +
    the headline throughput gauge. Called ONLY from designated sync
    points — publication must never add a host sync of its own."""
    reg = registry or get_registry()
    try:
        if step_seconds is not None:
            reg.histogram("vw_step_seconds",
                          "VW online-ring step latency "
                          "(dispatch to retirement)",
                          buckets=_VW_STEP_SECONDS_BUCKETS
                          ).observe(float(step_seconds))
        if examples_per_s is not None:
            reg.gauge("vw_examples_per_s",
                      "VW online-ring training throughput "
                      "(retired examples / wall second)"
                      ).set(float(examples_per_s))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail training
        warnings.warn(f"publish_vw_step_metrics failed: {e}", stacklevel=2)


#: bounded label set for bring-up probe outcomes — the raw outcome
#: strings carry free text (error details, durations) that must not
#: become unbounded label cardinality
_PROBE_CATEGORIES = (("healthy", "healthy"), ("init hang", "hang"),
                     ("spawn failed", "spawn_failed"),
                     ("parent", "parent_init"), ("seed", "seed"),
                     ("blacklisted", "blacklisted"), ("error", "error"))


def classify_probe_outcome(outcome: str) -> str:
    for prefix, cat in _PROBE_CATEGORIES:
        if outcome.startswith(prefix):
            return cat
    return "other"


def publish_probe_outcome(outcome: str,
                          registry: Optional[MetricsRegistry] = None
                          ) -> None:
    """One bring-up / retry probe record -> outcome-category counter
    (called from resilience.Attempt.record)."""
    reg = registry or get_registry()
    try:
        reg.counter("bringup_probe_outcomes_total",
                    "bring-up probe attempts by outcome category",
                    labels={"outcome": classify_probe_outcome(outcome)}
                    ).inc()
    except Exception as e:  # noqa: BLE001 - telemetry must not fail bring-up
        warnings.warn(f"publish_probe_outcome failed: {e}", stacklevel=2)


def publish_bringup(attempts: list, healthy: bool, window_s: float,
                    registry: Optional[MetricsRegistry] = None) -> None:
    """End-of-bring-up summary gauges (per-attempt counters land via
    Attempt.record as the attempts happen)."""
    reg = registry or get_registry()
    try:
        reg.gauge("bringup_last_window_seconds",
                  "wall seconds of the last bring-up window").set(window_s)
        reg.gauge("bringup_last_healthy",
                  "1 when the last bring-up reached an accelerator"
                  ).set(1.0 if healthy else 0.0)
        reg.gauge("bringup_last_probes",
                  "probe attempts in the last bring-up window"
                  ).set(len(attempts))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail bring-up
        warnings.warn(f"publish_bringup failed: {e}", stacklevel=2)


#: bounded label vocabularies for the train-on-traffic loop (ISSUE 19) —
#: mirrors resilience/rewardjoin.REFUSAL_REASONS (hardcoded here because
#: resilience already imports observability; the naming-lint test
#: asserts the two tuples stay identical)
_ONLINE_EVENT_KINDS = ("prediction", "reward")
_ONLINE_REFUSAL_REASONS = ("duplicate", "duplicate_prediction", "expired",
                           "unknown_key", "reward_timeout", "malformed")
_ONLINE_PUBLISH_OUTCOMES = ("published", "gate_refused", "error",
                            "rolled_back")
#: reward-to-applied lag spans the join horizon (sub-second synthetic
#: streams to minutes of real conversion delay)
_ONLINE_LAG_SECONDS_BUCKETS = (0.01, 0.05, 0.2, 1.0, 5.0, 30.0, 120.0,
                               600.0)
_ONLINE_SWAP_SECONDS_BUCKETS = (0.01, 0.05, 0.2, 1.0, 5.0, 30.0)


def publish_online_event(kind: str,
                         registry: Optional[MetricsRegistry] = None
                         ) -> None:
    """One ingested loop event (resilience/rewardjoin.py) -> bounded
    counter. Called from the joiner's ingest path, which is host-side
    dict work — no device sync to add."""
    reg = registry or get_registry()
    try:
        reg.counter("online_events_total",
                    "train-on-traffic loop events ingested by kind",
                    labels={"kind": kind if kind in _ONLINE_EVENT_KINDS
                            else "other"}).inc()
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the loop
        warnings.warn(f"publish_online_event failed: {e}", stacklevel=2)


def publish_online_refusal(reason: str,
                           registry: Optional[MetricsRegistry] = None
                           ) -> None:
    """One refused/evicted join (the exactly-once contract's counted
    refusal vocabulary, docs/ONLINE.md) -> bounded counter."""
    reg = registry or get_registry()
    try:
        reg.counter("online_join_refusals_total",
                    "reward-join refusals and evictions by reason",
                    labels={"reason": reason
                            if reason in _ONLINE_REFUSAL_REASONS
                            else "other"}).inc()
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the loop
        warnings.warn(f"publish_online_refusal failed: {e}", stacklevel=2)


def publish_online_apply(applied: int,
                         reward_lag_s=None,
                         examples_per_s: Optional[float] = None,
                         pending_keys: Optional[int] = None,
                         registry: Optional[MetricsRegistry] = None
                         ) -> None:
    """Joined-examples-applied telemetry, published from the loop's
    designated commit points (never per example): the applied counter,
    per-example reward->applied lag observations, headline loop
    throughput, and the join-buffer occupancy gauge."""
    reg = registry or get_registry()
    try:
        if applied:
            reg.counter("online_applied_examples_total",
                        "joined examples applied to the online learner"
                        ).inc(int(applied))
        if reward_lag_s:
            h = reg.histogram("online_reward_lag_seconds",
                              "reward event to learner-applied latency",
                              buckets=_ONLINE_LAG_SECONDS_BUCKETS)
            for lag in reward_lag_s:
                h.observe(float(lag))
        if examples_per_s is not None:
            reg.gauge("online_examples_per_s",
                      "train-on-traffic loop applied-example throughput"
                      ).set(float(examples_per_s))
        if pending_keys is not None:
            reg.gauge("online_pending_keys",
                      "reward-join buffer occupancy (pending predictions"
                      " + held out-of-order rewards)"
                      ).set(float(pending_keys))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the loop
        warnings.warn(f"publish_online_apply failed: {e}", stacklevel=2)


def publish_online_publish(outcome: str,
                           swap_seconds: Optional[float] = None,
                           registry: Optional[MetricsRegistry] = None
                           ) -> None:
    """One publish-leg attempt (train/online_loop.py ModelPublisher):
    outcome counter + the update->publish->swap latency histogram when
    the publish went out."""
    reg = registry or get_registry()
    try:
        reg.counter("online_publish_total",
                    "online-loop model publish attempts by outcome",
                    labels={"outcome": outcome
                            if outcome in _ONLINE_PUBLISH_OUTCOMES
                            else "other"}).inc()
        if swap_seconds is not None:
            reg.histogram("online_publish_swap_seconds",
                          "learner finalize to registry-publish latency",
                          buckets=_ONLINE_SWAP_SECONDS_BUCKETS
                          ).observe(float(swap_seconds))
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the loop
        warnings.warn(f"publish_online_publish failed: {e}", stacklevel=2)
