"""Incident flight recorder: anomaly triggers -> one atomic JSON bundle.

When a rollout rolls back at 91k rows/s the forensics are spread over N
process rings, the registry, and the coordinator's rollout record — and
the rings are BOUNDED, so waiting until a human looks means the evidence
is gone. The flight recorder keeps a short ring of recent request
summaries and system events (swap, rollback, retire, drain, autoscale,
chaos, SLO transitions — everything the TraceCollector drains from the
fleet's EventLogs), watches a small set of anomaly triggers, and on any
firing dumps an **incident bundle**: one atomic JSON (the PR 10
atomic-write helper — a crash mid-dump can never leave a torn bundle)
containing

- the assembled end-to-end trace trees of the slowest and failed
  requests in the window (gateway attempt spans parenting worker spans),
- the system-event ring (the rollback/retire/chaos story),
- the full registry snapshot,
- the coordinator's rollout state and every worker's `/health`,
- the SLO burn-rate status when a monitor is attached.

Triggers: swap rollback / rollout rolled_back (incl. canary loss), shed
spike over the window, windowed p99 breaching the armed baseline, SLO
breach transition. A per-reason cooldown stops a sustained anomaly from
flooding the disk. Clock, fetches, and the collector are injectable, so
tier-1 tests drive every trigger with no sleeps and no subprocess fleet
(the full fleet run rides the @slow measure_serving_load mini-run).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..resilience.elastic import atomic_write_bytes
from .collector import TraceCollector, _http_fetch as _http_json
from .metrics import MetricsRegistry, get_registry
from .slo import SLOMonitor, _family_buckets, windowed_quantile

__all__ = ["FlightRecorder", "BUNDLE_SCHEMA_VERSION"]

BUNDLE_SCHEMA_VERSION = 1


class FlightRecorder:
    """Bounded recent-history ring + anomaly triggers + bundle dumps.

    `tick()` is the whole control loop: poll the collector, ingest new
    system events, evaluate triggers, dump bundles. `start(interval_s)`
    runs ticks on a daemon thread for live fleets; tests call `tick()`
    directly under an injected clock.
    """

    def __init__(self, collector: TraceCollector, out_dir: str,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.time,
                 window_s: float = 60.0, cooldown_s: float = 30.0,
                 ring: int = 512, slowest_k: int = 5, failed_k: int = 10,
                 shed_spike: float = 50.0,
                 p99_factor: float = 3.0, p99_floor_ms: float = 5.0,
                 p99_family: str = "gateway_request_latency_seconds",
                 health_fn: Optional[Callable[[], Dict]] = None,
                 rollouts_fn: Optional[Callable[[], Dict]] = None,
                 workers_fn: Optional[Callable[[], List[Tuple[str, str]]]]
                 = None,
                 fetch: Callable[[str], Dict[str, Any]] = _http_json,
                 slo: Optional[SLOMonitor] = None,
                 chaos_bundles: bool = False,
                 metrics_label: str = "flightrecorder"):
        self.collector = collector
        self.out_dir = out_dir
        self.registry = registry if registry is not None else get_registry()
        self.clock = clock
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.ring = int(ring)
        self.slowest_k = int(slowest_k)
        self.failed_k = int(failed_k)
        self.shed_spike = float(shed_spike)
        self.p99_factor = float(p99_factor)
        self.p99_floor_ms = float(p99_floor_ms)
        self.p99_family = p99_family
        self.health_fn = health_fn
        self.rollouts_fn = rollouts_fn
        self.workers_fn = workers_fn
        self.fetch = fetch
        self.slo = slo
        #: ISSUE 20 — the production-day scorecard demands one incident
        #: bundle PER INJECTED FAULT CLASS, so a scenario run arms this
        #: to turn every `chaos` system event into a `chaos_<kind>`
        #: trigger (the per-reason cooldown still bounds disk churn).
        #: Dark by default: ordinary fleets bundle the chaos AFTERMATH
        #: (rollback, shed spike, SLO breach), not the injection itself.
        self.chaos_bundles = bool(chaos_bundles)
        self._lbl = {"instance": metrics_label}
        self._m_bundles: Dict[str, Any] = {}
        self._system: List[Dict[str, Any]] = []
        self._sys_seq = 0
        #: baseline p99 (ms) captured by arm_baseline(); None = the p99
        #: trigger stays dark (nothing to compare against)
        self.baseline_p99_ms: Optional[float] = None
        self._shed_samples: List[Tuple[float, float]] = []
        self._hist_samples: List[Tuple[float, Tuple[Dict, int]]] = []
        self._last_dump: Dict[str, float] = {}
        self._seq = 0
        self.incidents: List[str] = []     # bundle paths, oldest first
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- conveniences
    @classmethod
    def for_coordinator(cls, coordinator, collector: TraceCollector,
                        out_dir: str, service: str,
                        **kw) -> "FlightRecorder":
        """Recorder wired to one coordinator: rollout state, fleet
        /health, and its registry come along automatically."""
        def workers():
            return [(f"{s.host}:{s.port}", f"http://{s.host}:{s.port}")
                    for s in coordinator.routes(service)]
        kw.setdefault("registry", coordinator.registry)
        kw.setdefault("slo", getattr(coordinator, "slo", None))
        return cls(collector, out_dir,
                   health_fn=coordinator.health,
                   rollouts_fn=coordinator.rollouts_status,
                   workers_fn=workers, **kw)

    def _bundle_counter(self, reason: str):
        c = self._m_bundles.get(reason)
        if c is None:
            c = self.registry.counter(
                "incident_bundles_total", "incident bundles dumped",
                {**self._lbl, "reason": reason})
            self._m_bundles[reason] = c
        return c

    # -------------------------------------------------------------- baseline
    def arm_baseline(self) -> None:
        """Capture the p99 baseline the breach trigger compares against
        (call once the fleet is warm and serving steady traffic)."""
        p99 = self.registry.quantile(self.p99_family, 0.99)
        if p99 is None:
            # sum across label sets via a two-point window over one snap
            buckets = _family_buckets(
                self.registry.snapshot(families=[self.p99_family]),
                self.p99_family)
            p99 = windowed_quantile(({}, 0), buckets, 0.99)
        self.baseline_p99_ms = p99 * 1e3 if p99 is not None else None

    # ------------------------------------------------------------------ tick
    def tick(self) -> List[str]:
        """One control cycle. Returns the bundle paths written (if any)."""
        self.collector.poll()
        now = self.clock()
        new_events = self.collector.system_events(after_seq=self._sys_seq)
        written: List[str] = []
        with self._lock:
            for ev in new_events:
                self._sys_seq = max(self._sys_seq, ev["_seq"])
                self._system.append(ev)
            if len(self._system) > self.ring:
                del self._system[:len(self._system) - self.ring]
            # windowed samples for the rate triggers
            shed = (self._family_total("serving_shed_total")
                    + self._family_total("gateway_shed_total"))
            self._shed_samples.append((now, shed))
            self._hist_samples.append(
                (now, _family_buckets(
                    self.registry.snapshot(families=[self.p99_family]),
                    self.p99_family)))
            cutoff = now - self.window_s * 1.25
            self._shed_samples = [s for s in self._shed_samples
                                  if s[0] >= cutoff]
            self._hist_samples = [s for s in self._hist_samples
                                  if s[0] >= cutoff]
        for reason, detail in self._triggers(now, new_events):
            try:
                path = self._dump(reason, detail, now)
            except Exception:  # noqa: BLE001 - one failed dump (disk
                continue       # full) must not abort the other triggers;
                               # its cooldown is unconsumed, so it re-fires
            if path is not None:
                written.append(path)
        return written

    def _family_total(self, family: str) -> float:
        return self.registry.total(family)

    def _triggers(self, now: float, new_events: List[Dict]
                  ) -> List[Tuple[str, str]]:
        fired: List[Tuple[str, str]] = []
        # 1. swap rollback anywhere in the fleet
        for ev in new_events:
            if ev.get("span") == "swap" and \
                    str(ev.get("outcome", "")).startswith("rollback"):
                fired.append(("swap_rollback",
                              f"{ev.get('source')}: v{ev.get('version')} "
                              f"{ev.get('outcome')}"))
            # 2. rollout rolled back (covers canary loss, error-rate and
            # p99 breaches, timeout — the reason string says which)
            elif ev.get("span") == "rollout" and \
                    ev.get("state") == "rolled_back":
                fired.append(("rollout_rolled_back",
                              str(ev.get("reason"))))
            # 5. SLO breach transition (when a monitor feeds the logs)
            elif ev.get("span") == "slo" and ev.get("state") == "breach":
                fired.append(("slo_breach",
                              f"{ev.get('slo')}: fast "
                              f"{ev.get('burn_fast')} slow "
                              f"{ev.get('burn_slow')}"))
            # 6. (armed runs only) chaos injection itself — the
            # production-day scorecard's bundle-per-fault-class check
            elif self.chaos_bundles and ev.get("span") == "chaos":
                fired.append((f"chaos_{ev.get('kind', 'unknown')}",
                              f"injected {ev.get('kind')} "
                              f"(seed {ev.get('seed')})"))
        # 3. shed spike over the window
        with self._lock:
            if len(self._shed_samples) >= 2:
                base = self._window_base(self._shed_samples, now,
                                         self.window_s)
                if base is not None and base is not self._shed_samples[-1]:
                    d = self._shed_samples[-1][1] - base[1]
                    if d > self.shed_spike:
                        fired.append(("shed_spike",
                                      f"{d:.0f} sheds in {self.window_s:.0f}s"
                                      f" (> {self.shed_spike:.0f})"))
            # 4. windowed p99 vs armed baseline
            if self.baseline_p99_ms is not None \
                    and len(self._hist_samples) >= 2:
                base = self._window_base(self._hist_samples, now,
                                         self.window_s)
                if base is not None:
                    p99 = windowed_quantile(base[1],
                                            self._hist_samples[-1][1], 0.99)
                    if p99 is not None:
                        bar = max(self.baseline_p99_ms * self.p99_factor,
                                  self.p99_floor_ms)
                        if p99 * 1e3 > bar:
                            fired.append((
                                "p99_breach",
                                f"windowed p99 {p99 * 1e3:.1f}ms > "
                                f"{bar:.1f}ms (baseline "
                                f"{self.baseline_p99_ms:.1f}ms x "
                                f"{self.p99_factor})"))
        return fired

    @staticmethod
    def _window_base(samples, now, window_s):
        """Oldest sample actually INSIDE the window (retention keeps a
        25% margin past it, which must not widen the measured window)."""
        for s in samples:
            if now - s[0] <= window_s:
                return s
        return None

    # ------------------------------------------------------------------ dump
    def _workers_health(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, base_url in (self.workers_fn() if self.workers_fn
                               else ()):
            try:
                out[name] = self.fetch(base_url.rstrip("/") + "/health")
            except Exception as e:  # noqa: BLE001 - a dead worker's
                out[name] = {"unreachable": str(e)[:200]}  # absence IS data
        return out

    def _dump(self, reason: str, detail: str, now: float) -> Optional[str]:
        last = self._last_dump.get(reason)
        if last is not None and now - last < self.cooldown_s:
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
            system_events = [
                {k: v for k, v in e.items() if k != "_seq"}
                for e in self._system]
        trees = self.collector.assemble_all()   # ONE assembly pass
        bundle = {
            "schema_version": BUNDLE_SCHEMA_VERSION,
            "reason": reason,
            "detail": detail,
            "ts": now,
            "window_s": self.window_s,
            "traces": {
                "slowest": self.collector.slowest(self.slowest_k,
                                                  trees=trees),
                "failed": self.collector.failed(self.failed_k,
                                                trees=trees),
            },
            "system_events": system_events,
            "registry": self.registry.snapshot(),
            "rollouts": (self.rollouts_fn() if self.rollouts_fn else None),
            "coordinator_health": (self.health_fn() if self.health_fn
                                   else None),
            "workers_health": self._workers_health(),
            "slo": self.slo.status() if self.slo is not None else None,
        }
        path = f"{self.out_dir}/incident_{seq:04d}_{reason}.json"
        # the PR 10 atomic-write discipline: a crash mid-dump leaves the
        # previous bundles intact and at worst a stray temp file — never
        # a torn JSON that breaks the post-mortem tooling
        atomic_write_bytes(path, json.dumps(bundle, indent=1,
                                            default=str).encode())
        # cooldown is consumed only by a SUCCESSFUL write: a dump that
        # raised (disk full, health fetch blew up) must not suppress the
        # same reason re-firing on the next tick — that would leave NO
        # bundle for the incident at all
        self._last_dump[reason] = now
        self.incidents.append(path)
        self._bundle_counter(reason).inc()
        return path

    # ------------------------------------------------------------- lifecycle
    def start(self, interval_s: float = 1.0) -> "FlightRecorder":
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - the recorder must
                    pass           # outlive any one bad tick
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="flight-recorder")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
