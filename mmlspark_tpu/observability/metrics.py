"""Thread-safe metrics registry with Prometheus-text exposition.

The reference's observability story was ad-hoc `StopWatch` counters
surfaced as a diagnostics DataFrame (core/utils/StopWatch.scala:35,
VowpalWabbitBase.scala:268-303 perf stats); the repro inherited that
shape — `health()` dicts, per-server `stats` dicts, bench numbers that
only exist inside BENCH_*.json. This module is the single queryable
telemetry surface those all land on: counters, gauges (optionally
callback-backed), and fixed-bucket histograms with interpolated
p50/p95/p99, grouped into labeled families, exported as Prometheus text
(`GET /metrics` on every serving endpoint) and as a JSON-able snapshot
(embedded in bench JSON so the scrape and the bench record can never
disagree).

Determinism: snapshot/render order is sorted by (family, label items) —
two registries fed the same series in any order emit identical output,
so scrape diffs and bench-JSON diffs are meaningful.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "set_registry", "DEFAULT_LATENCY_BUCKETS"]


#: latency histogram bounds (seconds): sub-ms serving resolution at the
#: bottom (the asyncio listener's measured p50 is ~0.27 ms), decade-ish
#: spacing up to the 30 s request-timeout ceiling. +inf is implicit.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _label_key(labels: Optional[Dict[str, str]]
               ) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter (one labeled series)."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Settable value; `set_function` makes it collect-time computed
    (queue depth, dispatcher liveness — read fresh at every scrape)."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        """Install a collect-time callback; `None` FREEZES the gauge at its
        current value and drops the callback — a stopped server must not
        stay reachable (queue, handler, model arrays) through its own
        telemetry closure after the registry outlives it."""
        if fn is None:
            v = self.value  # one last read through the callback
            with self._lock:
                self._fn = None
                self._value = v
            return
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            v = float(fn())
        except Exception:  # a dead callback must not kill the scrape
            with self._lock:
                return self._value
        with self._lock:
            self._value = v
            return v


class Histogram:
    """Fixed-bucket histogram with interpolated quantiles.

    Buckets are cumulative upper bounds (Prometheus `le` semantics) with
    an implicit +inf bucket. `quantile(q)` linearly interpolates inside
    the bucket holding the target rank — accurate to one bucket width,
    which the default latency bounds keep proportional to the value.
    """

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count",
                 "_min", "_max")

    def __init__(self, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # + the +inf bucket
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        # bisect without the import: bucket index by linear scan is fine
        # for <= ~20 bounds and avoids allocation on the hot path
        i = 0
        bounds = self.bounds
        while i < len(bounds) and v > bounds[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Interpolated q-quantile (q in [0, 1]); None when empty. Values
        beyond the last finite bound report the observed max (the +inf
        bucket has no width to interpolate in)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            total = self._count
            if total == 0:
                return None
            counts = list(self._counts)
            vmin, vmax = self._min, self._max
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.bounds):     # +inf bucket
                    return vmax
                lo = self.bounds[i - 1] if i > 0 else min(vmin, 0.0)
                hi = self.bounds[i]
                frac = (rank - cum) / c
                return min(max(lo + (hi - lo) * frac, vmin), vmax)
            cum += c
        return vmax

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            out: Dict[str, Any] = {"count": self._count,
                                   "sum": round(self._sum, 6)}
        out["buckets"] = {("+Inf" if i >= len(self.bounds)
                           else repr(self.bounds[i])): c
                          for i, c in enumerate(counts)}
        for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            v = self.quantile(q)
            if v is not None:
                out[name] = round(v, 6)
        return out


_KINDS = ("counter", "gauge", "histogram")


class _Family:
    __slots__ = ("name", "kind", "help", "series", "buckets")

    def __init__(self, name: str, kind: str, help_: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.kind = kind
        self.help = help_
        self.series: Dict[Tuple[Tuple[str, str], ...], Any] = {}
        self.buckets = buckets


class MetricsRegistry:
    """Families of labeled Counter/Gauge/Histogram series.

    `counter/gauge/histogram(name, labels=...)` returns the (created-once)
    series for that label set — callers keep the handle and hit only the
    series lock on the hot path. Name collisions across kinds raise: one
    name, one kind, forever (the Prometheus contract).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # -------------------------------------------------------------- create
    def _family(self, name: str, kind: str, help_: str,
                buckets: Optional[Sequence[float]] = None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help_, buckets)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            return fam

    def _series(self, name: str, kind: str, help_: str,
                labels: Optional[Dict[str, str]],
                buckets: Optional[Sequence[float]] = None):
        fam = self._family(name, kind, help_, buckets)
        key = _label_key(labels)
        with self._lock:
            s = fam.series.get(key)
            if s is None:
                if kind == "counter":
                    s = Counter()
                elif kind == "gauge":
                    s = Gauge()
                else:
                    s = Histogram(fam.buckets or DEFAULT_LATENCY_BUCKETS)
                fam.series[key] = s
            return s

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._series(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._series(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._series(name, "histogram", help, labels, buckets)

    # --------------------------------------------------------------- query
    def _sorted_families(self) -> List[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def total(self, name: str) -> float:
        """Sum of a counter/gauge family across all label sets (0.0 when
        the family does not exist) — the cross-instance reconciliation
        helper chaos tests and scripts read."""
        with self._lock:
            fam = self._families.get(name)
            series = list(fam.series.values()) if fam else []
        return float(sum(s.value for s in series))

    def quantile(self, name: str, q: float,
                 labels: Optional[Dict[str, str]] = None
                 ) -> Optional[float]:
        """q-quantile of one histogram series (None when absent/empty)."""
        with self._lock:
            fam = self._families.get(name)
            s = fam.series.get(_label_key(labels)) if fam else None
        if s is None:
            return None
        return s.quantile(q)

    def snapshot(self, families: Optional[Sequence[str]] = None
                 ) -> Dict[str, Any]:
        """JSON-able view of every series, deterministically ordered
        (families sorted by name, series by label items). `families`
        restricts the view — the periodic samplers (SLO monitor, flight
        recorder) read 1-3 families per tick and must not serialize the
        whole registry under its lock every time."""
        out: Dict[str, Any] = {}
        wanted = None if families is None else set(families)
        for fam in self._sorted_families():
            if wanted is not None and fam.name not in wanted:
                continue
            with self._lock:
                items = sorted(fam.series.items())
            rows = []
            for key, s in items:
                row: Dict[str, Any] = {"labels": dict(key)}
                if fam.kind == "histogram":
                    row.update(s.snapshot())
                else:
                    row["value"] = round(s.value, 6)
                rows.append(row)
            out[fam.name] = {"kind": fam.kind, "help": fam.help,
                             "series": rows}
        return out

    # ------------------------------------------------------------- render
    @staticmethod
    def _fmt_labels(key: Tuple[Tuple[str, str], ...],
                    extra: Optional[Tuple[Tuple[str, str], ...]] = None
                    ) -> str:
        pairs = list(key) + list(extra or ())
        if not pairs:
            return ""
        body = ",".join(
            '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"')
                         .replace("\n", "\\n")) for k, v in pairs)
        return "{" + body + "}"

    @staticmethod
    def _fmt_value(v: float) -> str:
        if v == math.inf:
            return "+Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 of the whole registry."""
        lines: List[str] = []
        for fam in self._sorted_families():
            with self._lock:
                items = sorted(fam.series.items())
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, s in items:
                if fam.kind == "histogram":
                    snap = s.snapshot()
                    cum = 0
                    counts = list(snap["buckets"].values())
                    for i, b in enumerate(list(s.bounds) + [math.inf]):
                        cum += counts[i]
                        le = (("le", self._fmt_value(b)),)
                        lines.append(f"{fam.name}_bucket"
                                     f"{self._fmt_labels(key, le)} {cum}")
                    lines.append(f"{fam.name}_sum{self._fmt_labels(key)} "
                                 f"{repr(snap['sum'])}")
                    lines.append(f"{fam.name}_count{self._fmt_labels(key)} "
                                 f"{snap['count']}")
                else:
                    lines.append(f"{fam.name}{self._fmt_labels(key)} "
                                 f"{self._fmt_value(s.value)}")
        return "\n".join(lines) + "\n"

    def remove(self, name: str, labels: Optional[Dict[str, str]] = None
               ) -> bool:
        """Drop one labeled series (or, with labels=None, the whole
        family). Returns whether anything was removed. Server stop() only
        FREEZES its series (final counts stay scrapeable); a long-lived
        process that churns through many servers calls this — e.g.
        `reg.remove("serving_queue_depth", {"instance": "serving-3"})` —
        to retire a dead instance's series from scrapes and snapshots."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return False
            if labels is None:
                del self._families[name]
                return True
            removed = fam.series.pop(_label_key(labels), None) is not None
            if not fam.series:
                del self._families[name]
            return removed

    def reset(self) -> None:
        """Drop every family (test isolation for the global registry)."""
        with self._lock:
            self._families.clear()


_default_registry = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global default registry: serving servers, the gateway,
    the profiling bridge, and the bench snapshot all land here unless
    handed an explicit registry."""
    with _default_lock:
        return _default_registry


def set_registry(reg: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests); returns the previous."""
    global _default_registry
    with _default_lock:
        prev, _default_registry = _default_registry, reg
        return prev
