"""Cross-process trace assembly: drain every EventLog, build trace trees.

PR 8 gave every process a bounded EventLog of per-hop spans and PR 12-13
grew the system into a real fleet — but spans still died inside the
process that recorded them: "explain this request end to end" meant
hand-grepping N rings. The reference stack leans on driver-side
aggregation for exactly this (SURVEY §0 HTTP-on-Spark / Spark Serving:
the driver owns the routing table AND the aggregate view), and arxiv
2605.25645's serving-economics argument makes per-request tail-latency
attribution (host path vs device dispatch) a first-class measurement.

`TraceCollector` is that driver-side aggregator:

- every worker and the gateway expose their ring over `GET
  /trace?since=<ts>` (io/serving.py, io/distributed_serving.py) — a
  cursor drain, not a snapshot, so polling is O(new events);
- the collector pulls all rings (HTTP for remote processes, direct
  EventLog references in-process) and indexes events by `X-Trace-Id`;
- `trace(tid)` assembles the end-to-end TREE: gateway `forward_attempt`
  spans parent the worker's `queue_wait -> batch_assembly ->
  device_dispatch -> reply` spans for the same trace id (matched by the
  attempt's `worker` endpoint and time window, with a per-hop
  clock-skew tolerance since each process stamps its own wall clock);
- `slowest(k)` / `failed()` answer the two operator questions directly.

Everything is injectable (fetch, clock) so tier-1 tests drive the whole
assembly against scripted rings with no sockets and no sleeps; the
polling thread exists for the live fleet (scripts/measure_serving_load,
scripts/fleet_status).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry, get_registry
from .tracing import EventLog

__all__ = ["TraceCollector", "REQUEST_SPANS", "SYSTEM_SPANS"]

#: spans that belong to one request's life (worker + gateway hops)
REQUEST_SPANS = ("queue_wait", "batch_assembly", "device_dispatch",
                 "reply", "forward_attempt", "shed", "expired")

#: spans recording fleet/system transitions, not requests — the flight
#: recorder's feed (observability/flightrecorder.py)
SYSTEM_SPANS = ("swap", "rollout", "retire", "drain", "autoscale", "chaos",
                "slo")

#: worker span order inside one hop — used when wall clocks tie or skew
_WORKER_ORDER = {"queue_wait": 0, "batch_assembly": 1,
                 "device_dispatch": 2, "reply": 3}


def _http_fetch(url: str, timeout: float = 5.0) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


class _Source:
    """One ring to drain: either a /trace URL or an in-process EventLog."""

    __slots__ = ("name", "url", "log", "cursor", "role", "endpoint",
                 "live")

    def __init__(self, name: str, url: Optional[str], log: Optional[EventLog],
                 role: str, endpoint: Optional[str]):
        self.name = name
        self.url = url
        self.log = log
        self.role = role            # "gateway" | "worker"
        #: "host:port" workers are addressed by in gateway forward spans —
        #: the join key that parents worker spans under the right attempt
        self.endpoint = endpoint
        self.cursor = 0.0
        #: coordinator-managed worker sources are marked dead when they
        #: leave the routing table (retired/killed): polling a departed
        #: worker's URL stalls the whole drain loop 5 s per cycle —
        #: exactly while a shrinking fleet needs the collector most. The
        #: cursor is KEPT, so a healed re-registration resumes without
        #: re-ingesting (no duplicate spans)
        self.live = True


class TraceCollector:
    """Pulls every hop's EventLog and assembles end-to-end trace trees.

    `add_worker` / `add_gateway` register sources by `/trace` URL (remote
    process) or by EventLog reference (in-process). `poll()` drains each
    source from its cursor; `trace(tid)` returns the assembled tree;
    `slowest(k)` / `failed()` / `summaries()` are the query surface.
    `system_events()` exposes drained SYSTEM_SPANS events (swap, rollout,
    retire, autoscale, chaos) for the flight recorder.

    Memory is bounded: at most `max_traces` traces are retained (LRU by
    last-event time) and at most `max_events_per_trace` events per trace.
    """

    def __init__(self, clock: Callable[[], float] = time.time,
                 skew_tolerance_s: float = 0.25,
                 max_traces: int = 4096, max_events_per_trace: int = 64,
                 max_system_events: int = 1024,
                 fetch: Callable[[str], Dict[str, Any]] = _http_fetch,
                 registry: Optional[MetricsRegistry] = None,
                 metrics_label: str = "collector"):
        self.clock = clock
        self.skew_tolerance_s = float(skew_tolerance_s)
        self.max_traces = int(max_traces)
        self.max_events_per_trace = int(max_events_per_trace)
        self.fetch = fetch
        self._sources: List[_Source] = []
        #: trace_id -> list of (source_name, event); insertion order = LRU
        self._traces: "OrderedDict[str, List[Tuple[str, Dict]]]" = \
            OrderedDict()
        self._system: List[Dict[str, Any]] = []
        self._max_system = int(max_system_events)
        self._system_seq = 0    # monotonic cursor for system-event readers
        self._lock = threading.Lock()
        self._poll_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = registry if registry is not None else get_registry()
        lbl = {"instance": metrics_label}
        self._m_polls = reg.counter(
            "collector_polls_total", "source drains attempted", lbl)
        self._m_events = reg.counter(
            "collector_events_total", "events drained from all sources", lbl)
        self._m_errors = reg.counter(
            "collector_poll_errors_total",
            "source drains that failed (unreachable ring)", lbl)
        self._g_traces = reg.gauge(
            "collector_traces", "traces currently retained", lbl)
        self._g_traces.set_function(lambda: float(len(self._traces)))

    # ------------------------------------------------------------- sources
    def add_gateway(self, name: str, *, url: Optional[str] = None,
                    event_log: Optional[EventLog] = None) -> None:
        self._add(name, url, event_log, "gateway", None)

    def add_worker(self, name: str, *, endpoint: str,
                   url: Optional[str] = None,
                   event_log: Optional[EventLog] = None) -> None:
        """`endpoint` is the "host:port" the gateway forwards to — the key
        that joins this worker's spans to gateway forward_attempt spans."""
        self._add(name, url, event_log, "worker", endpoint)

    def _add(self, name, url, log, role, endpoint) -> None:
        if (url is None) == (log is None):
            raise ValueError("give exactly one of url= or event_log=")
        with self._lock:
            for s in self._sources:
                if s.name != name:
                    continue
                if s.url == url and s.log is log \
                        and s.endpoint == endpoint:
                    return  # idempotent re-add (fleet re-discovery)
                # same identity, new address: a worker RESTARTED on a new
                # port (the PR 13 re-register storm). Keeping the stale
                # source would poll a dead URL forever and the new
                # incarnation's spans would never parent (the gateway's
                # attempt spans name the NEW endpoint) — replace it and
                # restart the cursor on the fresh ring
                s.url, s.log, s.role = url, log, role
                s.endpoint = endpoint
                s.cursor = 0.0
                s.live = True
                return
            self._sources.append(_Source(name, url, log, role, endpoint))

    @classmethod
    def for_coordinator(cls, coordinator, service: str,
                        **kw) -> "TraceCollector":
        """Collector over one coordinator's fleet: the gateway's own ring
        in-process, every routed worker over its `/trace` endpoint. Call
        `refresh_workers()` (or just `poll()`) after fleet changes —
        newly registered workers are picked up, departed ones simply stop
        yielding events."""
        col = cls(**kw)
        col.add_gateway(coordinator.metrics_label,
                        event_log=coordinator.events)
        col._coordinator = coordinator
        col._service = service
        col.refresh_workers()
        return col

    def refresh_workers(self) -> None:
        coord = getattr(self, "_coordinator", None)
        if coord is None:
            return
        routed = set()
        for s in coord.routes(self._service):
            routed.add(f"{s.host}:{s.port}")
            self.add_worker(f"{s.machine}:{s.partition}",
                            endpoint=f"{s.host}:{s.port}",
                            url=f"http://{s.host}:{s.port}/trace")
        # evicted/retired workers go dormant (cursor kept for a heal);
        # a chaos-blip eviction costs at most the polls until re-register
        with self._lock:
            for src in self._sources:
                if src.role == "worker" and src.url is not None:
                    src.live = src.endpoint in routed

    # --------------------------------------------------------------- drain
    def poll(self) -> int:
        """Drain every live source from its cursor. Returns events
        ingested. A source that fails to answer is counted and skipped —
        the other rings still drain (a dead worker must not blind the
        collector). Serialized under `_poll_lock`: two concurrent
        pollers (the collector's own thread + a flight recorder's tick)
        would otherwise read the same cursor and ingest every drain
        twice — duplicated spans in every assembled tree."""
        with self._poll_lock:
            return self._poll_locked()

    def _poll_locked(self) -> int:
        self.refresh_workers()
        with self._lock:
            sources = [s for s in self._sources if s.live]
        n = 0
        for src in sources:
            self._m_polls.inc()
            try:
                if src.log is not None:
                    evs, cursor = src.log.drain(src.cursor)
                else:
                    payload = self.fetch(f"{src.url}?since={src.cursor}")
                    evs = payload.get("events", [])
                    cursor = float(payload.get("now", src.cursor))
            except Exception:  # noqa: BLE001 - one dead ring must not
                self._m_errors.inc()   # blind the others
                continue
            src.cursor = max([src.cursor, cursor]
                             + [e["ts"] for e in evs])
            if evs:
                self._ingest(src, evs)
                n += len(evs)
        if n:
            self._m_events.inc(n)
        return n

    def _ingest(self, src: _Source, evs: List[Dict[str, Any]]) -> None:
        with self._lock:
            for ev in evs:
                if ev.get("span") in SYSTEM_SPANS:
                    self._system_seq += 1
                    self._system.append({**ev, "source": src.name,
                                         "_seq": self._system_seq})
                    if len(self._system) > self._max_system:
                        del self._system[:len(self._system)
                                         - self._max_system]
                    continue
                tid = ev.get("trace_id")
                if not tid:
                    continue
                lst = self._traces.get(tid)
                if lst is None:
                    lst = self._traces[tid] = []
                else:
                    self._traces.move_to_end(tid)
                if len(lst) < self.max_events_per_trace:
                    lst.append((src.name, ev))
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)

    # ------------------------------------------------------------ assembly
    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def system_events(self, after_seq: int = 0) -> List[Dict[str, Any]]:
        """Drained system events with `_seq` > after_seq (the flight
        recorder's cursor read)."""
        with self._lock:
            return [dict(e) for e in self._system if e["_seq"] > after_seq]

    def trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Assemble one end-to-end trace tree.

        Shape: {"trace_id", "status", "duration_s", "hops": [...]} where
        each hop is an event dict plus "source", and a gateway
        `forward_attempt` hop carries the matched worker spans under
        "children" (ordered queue_wait -> ... -> reply). Matching is by
        the attempt's `worker` endpoint and its time window widened by
        `skew_tolerance_s` — each process stamps its own wall clock, so
        exact ordering across hops cannot be trusted below the skew
        bound; within one hop the span pipeline order is authoritative.
        """
        with self._lock:
            tagged = list(self._traces.get(trace_id) or ())
        if not tagged:
            return None
        roles = {s.name: s for s in self._sources}
        gw: List[Dict] = []
        by_worker: Dict[str, List[Dict]] = {}
        loose: List[Dict] = []
        for name, ev in tagged:
            src = roles.get(name)
            e = {**ev, "source": name}
            if src is not None and src.role == "gateway":
                gw.append(e)
            elif src is not None and src.role == "worker":
                by_worker.setdefault(src.endpoint or name, []).append(e)
            else:
                loose.append(e)
        for evs in by_worker.values():
            evs.sort(key=lambda e: (e["ts"],
                                    _WORKER_ORDER.get(e["span"], 9)))
        gw.sort(key=lambda e: e["ts"])
        claimed: set = set()
        hops: List[Dict[str, Any]] = []
        skew = self.skew_tolerance_s
        for e in gw:
            if e["span"] == "forward_attempt" and e.get("worker"):
                # the attempt's ts is stamped at COMPLETION; its window is
                # [ts - dur - skew, ts + skew] on the worker's clock
                t_hi = e["ts"] + skew
                t_lo = e["ts"] - float(e.get("dur_s") or 0.0) - skew
                kids = []
                for w in by_worker.get(e["worker"], ()):
                    wid = id(w)
                    if wid in claimed or not t_lo <= w["ts"] <= t_hi:
                        continue
                    claimed.add(wid)
                    kids.append(w)
                kids.sort(key=lambda w: (_WORKER_ORDER.get(w["span"], 9),
                                         w["ts"]))
                hops.append({**e, "children": kids})
            else:
                hops.append(e)
        # direct-hit worker spans (no gateway in the path) and spans whose
        # attempt window missed (skew larger than tolerated): top level,
        # never dropped — a lossy assembler would hide exactly the
        # misbehaving hop an incident needs
        for endpoint, evs in sorted(by_worker.items()):
            orphans = [w for w in evs if id(w) not in claimed]
            if orphans:
                hops.extend(orphans)
        hops.extend(loose)
        status = None
        duration = None
        for e in hops:
            if e["span"] == "reply":
                status = e.get("status", status)
                duration = e.get("dur_s", duration)
            elif e["span"] in ("shed", "expired") and status is None:
                status = e.get("status")
        if duration is None and hops:
            ts = [e["ts"] for e in hops]
            duration = round(max(ts) - min(ts), 6)
        return {"trace_id": trace_id, "status": status,
                "duration_s": duration, "hops": hops}

    # ------------------------------------------------------------- queries
    def assemble_all(self) -> List[Dict[str, Any]]:
        """Every retained trace assembled once — pass the result to
        `slowest`/`failed` when querying both (the flight recorder's
        dump path): re-assembling 2x per dump would stall ingest exactly
        while the fleet is degraded."""
        return [t for t in (self.trace(tid) for tid in self.trace_ids())
                if t is not None]

    def summaries(self) -> List[Dict[str, Any]]:
        """One flat row per retained trace (the flight recorder's request
        ring): {trace_id, status, duration_s, hops}."""
        return [{"trace_id": t["trace_id"], "status": t["status"],
                 "duration_s": t["duration_s"], "hops": len(t["hops"])}
                for t in self.assemble_all()]

    def slowest(self, k: int = 5,
                trees: Optional[List[Dict[str, Any]]] = None
                ) -> List[Dict[str, Any]]:
        done = [t for t in (trees if trees is not None
                            else self.assemble_all())
                if t["duration_s"] is not None]
        done.sort(key=lambda t: -t["duration_s"])
        return done[:k]

    def failed(self, limit: int = 20,
               trees: Optional[List[Dict[str, Any]]] = None
               ) -> List[Dict[str, Any]]:
        """Traces whose final status is not a 2xx, or that record a
        failed/no-worker forward attempt anywhere in the tree."""
        out = []
        for t in (trees if trees is not None else self.assemble_all()):
            bad_status = t["status"] is not None and not \
                (200 <= int(t["status"]) < 300)
            bad_hop = any(
                h.get("span") == "forward_attempt"
                and h.get("outcome") not in ("ok", None)
                for h in t["hops"])
            if bad_status or bad_hop:
                out.append(t)
            if len(out) >= limit:
                break
        return out

    # ------------------------------------------------------------ lifecycle
    def start(self, interval_s: float = 0.5) -> "TraceCollector":
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll()
                except Exception:  # noqa: BLE001 - one bad poll must not
                    pass           # kill the drain loop
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="trace-collector")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
        self._g_traces.set_function(None)
