"""train/ layer — auto-featurizing trainers + model statistics
(reference: train/, 6 files, 1232 LoC)."""

from .compute_statistics import (ComputeModelStatistics,
                                 ComputePerInstanceStatistics)
from .metrics import MetricConstants
from .trainers import (TrainClassifier, TrainedClassifierModel,
                       TrainedRegressorModel, TrainRegressor)

__all__ = [
    "TrainClassifier", "TrainedClassifierModel",
    "TrainRegressor", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
    "MetricConstants",
]
