"""train/ layer — auto-featurizing trainers + model statistics
(reference: train/, 6 files, 1232 LoC)."""

from .compute_statistics import (ComputeModelStatistics,
                                 ComputePerInstanceStatistics)
from .metrics import MetricConstants
from .online_loop import (HoldoutGate, ModelPublisher,
                          OnlineLearnerRunner, offline_replay)
from .trainers import (TrainClassifier, TrainedClassifierModel,
                       TrainedRegressorModel, TrainRegressor)

__all__ = [
    "TrainClassifier", "TrainedClassifierModel",
    "TrainRegressor", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
    "MetricConstants",
    "OnlineLearnerRunner", "HoldoutGate", "ModelPublisher",
    "offline_replay",
]
