"""Fault-tolerant train-on-traffic loop (ROADMAP item 2, ISSUE 19).

Closes the reference's one end-to-end capability we had all the parts
for but had never wired: served predictions -> delayed rewards ->
incremental VW updates -> registry publish -> canary rollout, surviving
the faults a production loop actually sees. The pieces:

- `RewardJoiner` (resilience/rewardjoin.py) turns the at-least-once
  event stream into exactly-once training examples.
- `OnlineLearnerRunner` (here) drains joined examples into the PR 16
  `VWOnlineRing` and snapshots {learner carry, joiner state, event-log
  cursor} as ONE atomic unit through the PR 10 `CheckpointStore`
  (schema-v2 sidecar: learner state digest + reward cursor in the
  manifest `extra`). A SIGTERM/preemption mid-update resumes from the
  snapshot with zero lost and zero double-applied rewards — the proof
  is `offline_replay`: an uninterrupted run of the SAME seeded event
  log lands on a bit-identical learner state digest.
- `HoldoutGate` + `ModelPublisher` (here) are the publish leg: every
  k-th joined example is diverted to a sliding held-out window (never
  trained on), the candidate must not regress against the incumbent on
  that window to publish, and the same gate plugs into the serving
  coordinator via `add_rollout_monitor` so a worse model that DOES get
  out auto-rolls back exactly like a corrupt artifact.

Determinism contract (what makes the digest-parity proof valid): the
VW minibatch step is BATCHED — every row in a minibatch sees the same
pre-batch weights — so the grouping of examples into minibatches is
part of the numerics, and a `ring.flush()` (which closes the current
partial minibatch with inert zero-weight pad rows) is only
digest-neutral if it happens at the SAME example ordinals in every run
being compared. The loop therefore keys every flush-bearing cadence —
snapshot boundaries, publish points, holdout diversion — on the
JOINED-EXAMPLE ordinal, never the wall clock or the read batching:
snapshots fire exactly at multiples of `snapshot_every`, publishes at
multiples of `publish_every` (constrained to a multiple of
`snapshot_every`, so a run without a publisher — the replay oracle —
still flushes at the identical ordinals), and the joiner's expiry runs
on the event-time watermark. Submit-call granularity does NOT matter:
the ring buffers submitted rows into fixed minibatches regardless of
call chunking; only flush points do.

Hot path discipline: `step` / `_ingest_events` / `_apply_staged` carry
zero host syncs (AST-linted, tests/test_fit_pipeline.py) — host array
building is delegated to the module-level `_coerce_rows`, and every
device readback lives in the designated commit points
(`_commit_snapshot` / `_publish` / `finalize`).
"""

from __future__ import annotations

import base64
import json
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..models.vw.sgd import (state_digest, state_from_bytes,
                             state_to_bytes)
from ..resilience.elastic import Preempted
from ..resilience.rewardjoin import RewardJoiner

__all__ = ["OnlineLearnerRunner", "HoldoutGate", "ModelPublisher",
           "offline_replay"]


def _local_device_count() -> int:
    try:
        import jax
        return int(jax.local_device_count())
    except Exception:  # noqa: BLE001 - no backend = single-device
        return 1


def _coerce_rows(staged: List[Dict[str, Any]], width: int):
    """Host-side row packing for one staged chunk: pad each example's
    hashed (indices, values) to the loop's fixed row width with inert
    (index 0, value 0.0) entries — a zero-VALUE feature contributes
    nothing to the margin, the gradient, or the adagrad accumulators,
    the same inertness argument as the ring's zero-WEIGHT flush pad.
    Module-level on purpose: keeps the host-array tokens out of the
    linted hot-path function bodies (the `_coerce_rows` idiom)."""
    n = len(staged)
    idx = np.zeros((n, width), np.int32)
    val = np.zeros((n, width), np.float32)
    labels = np.zeros(n, np.float32)
    weights = np.ones(n, np.float32)
    for r, ex in enumerate(staged):
        k = len(ex["indices"])
        if k > width:
            raise ValueError(
                f"example has {k} features, loop row_width is {width}")
        idx[r, :k] = ex["indices"]
        val[r, :k] = ex["values"]
        labels[r] = ex["label"]
        weights[r] = ex["weight"]
    return idx, val, labels, weights


def _eval_holdout(state, examples, width: int) -> Optional[Dict[str, float]]:
    """IPS-weighted squared error of the linear margin against observed
    cost on the held-out window (host-side numpy — gate evaluation is a
    commit point, never the hot path). Lower is better; `policy_value`
    reports the IPS estimate of the cost the argmin policy would incur,
    the regret-facing number docs/ONLINE.md tracks."""
    if not examples:
        return None
    idx, val, labels, weights = _coerce_rows(list(examples), width)
    w = np.asarray(state.w)
    bias = float(np.asarray(state.bias))
    margins = (val * w[idx]).sum(axis=1) + bias
    se = (margins - labels) ** 2
    wsum = float(weights.sum())
    return {
        "examples": len(examples),
        "weighted_mse": float((se * weights).sum() / max(wsum, 1e-9)),
        "policy_value": float((labels * weights).sum() / max(wsum, 1e-9)),
    }


class HoldoutGate:
    """Sliding held-out window + the regression decision on it.

    The runner diverts every `holdout_every`-th joined example here
    INSTEAD of training on it (deterministic by joined ordinal, so the
    split survives preempt/resume bit-for-bit). `admit` gates a publish:
    the candidate must not be worse than the incumbent by more than
    `tolerance` (relative) on the current window. `rollout_monitor`
    wraps the same decision for the serving coordinator's
    `add_rollout_monitor`: while a canary rollout is active, the canary
    version is re-scored against the incumbent on the LIVE window every
    tick — a worse model auto-rolls back like a corrupt artifact."""

    def __init__(self, width: int, window: int = 256,
                 tolerance: float = 0.10, min_delta: float = 1e-4):
        self.width = int(width)
        self.window = deque(maxlen=int(window))
        self.tolerance = float(tolerance)
        #: absolute regression floor: a near-perfect incumbent (mse ~ 0)
        #: must not veto an equally-good candidate over float dust
        self.min_delta = float(min_delta)
        self.last_eval: Optional[Dict[str, Any]] = None

    def add(self, example: Dict[str, Any]) -> None:
        self.window.append(example)

    def __len__(self) -> int:
        return len(self.window)

    def admit(self, candidate_state, incumbent_state) -> Optional[str]:
        """None = publish may proceed; a string = the counted refusal
        reason. No incumbent or an empty window always admits (there is
        nothing to regress against)."""
        cand = _eval_holdout(candidate_state, self.window, self.width)
        self.last_eval = {"candidate": cand}
        if cand is None or incumbent_state is None:
            return None
        inc = _eval_holdout(incumbent_state, self.window, self.width)
        self.last_eval["incumbent"] = inc
        if cand["weighted_mse"] > inc["weighted_mse"] * (1 + self.tolerance) \
                + self.min_delta:
            return (f"holdout regression: candidate mse "
                    f"{cand['weighted_mse']:.6f} vs incumbent "
                    f"{inc['weighted_mse']:.6f} (+>{self.tolerance:.0%})")
        return None

    def rollout_monitor(self, registry) -> Callable[[], Optional[str]]:
        """A coordinator rollout gate: score CANARY vs CURRENT from the
        model registry on the live window; a regression is a breach
        reason (rolls the fleet back). Versions whose payloads are not
        loop-published weights score as None and pass — this gate only
        judges models it understands."""
        def monitor() -> Optional[str]:
            canary, current = registry.canary(), registry.current()
            if canary is None or not self.window:
                return None
            cand = self._load_state(registry, canary)
            if cand is None:
                return None
            inc = (self._load_state(registry, current)
                   if current is not None else None)
            reason = self.admit(cand, inc)
            return (f"canary v{canary} {reason}" if reason else None)
        return monitor

    @staticmethod
    def _load_state(registry, version: int):
        try:
            vdir, man = registry.resolve(version)
            if "weights.npz" not in man.get("files", {}):
                return None
            import os
            with open(os.path.join(vdir, "weights.npz"), "rb") as fh:
                return state_from_bytes(fh.read())
        except Exception:  # noqa: BLE001 - unreadable/corrupt = not judged
            return None


class ModelPublisher:
    """Finalize the learner into the ModelRegistry: weights npz (the
    `state_to_bytes` codec) + meta.json {digest, joined ordinal, ndev,
    holdout eval}, optional golden probe, never set_current — promotion
    is the canary rollout's job (`rollout_fn`, e.g. a closure over
    `coordinator.start_rollout`). Keeps the last published state in
    memory as the gate's incumbent until a registry current exists."""

    def __init__(self, registry, *, gate: Optional[HoldoutGate] = None,
                 rollout_fn: Optional[Callable[[int], Any]] = None,
                 golden_fn: Optional[Callable] = None,
                 set_current: bool = False):
        self.registry = registry
        self.gate = gate
        self.rollout_fn = rollout_fn
        self.golden_fn = golden_fn
        self.set_current = bool(set_current)
        self.last_published_state = None
        self.counts: Dict[str, int] = {"published": 0, "gate_refused": 0,
                                       "error": 0}

    def _incumbent_state(self):
        cur = self.registry.current()
        if cur is not None:
            state = HoldoutGate._load_state(self.registry, cur)
            if state is not None:
                return state
        return self.last_published_state

    def publish(self, state, meta: Dict[str, Any]) -> Optional[int]:
        """Gate, then publish; returns the version or None if refused.
        A failing publish counts `error` and raises — the loop's caller
        decides whether a broken registry is fatal."""
        from ..observability.bridge import publish_online_publish
        if self.gate is not None:
            reason = self.gate.admit(state, self._incumbent_state())
            if reason is not None:
                self.counts["gate_refused"] += 1
                publish_online_publish("gate_refused")
                return None
            meta = dict(meta, holdout=self.gate.last_eval)
        golden_kw = {}
        if self.golden_fn is not None:
            body, reply_sha = self.golden_fn(state)
            golden_kw = {"golden_body": body,
                         "golden_reply_sha256": reply_sha}
        try:
            version = self.registry.publish(
                files={
                    "weights.npz": state_to_bytes(state),
                    "meta.json": json.dumps(meta, sort_keys=True,
                                            default=str).encode(),
                },
                extra={"kind": "online_loop",
                       "learner_digest": meta.get("learner_digest")},
                set_current=self.set_current, **golden_kw)
        except Exception:
            self.counts["error"] += 1
            publish_online_publish("error")
            raise
        self.counts["published"] += 1
        self.last_published_state = state
        if self.rollout_fn is not None:
            self.rollout_fn(version)
        return version


class OnlineLearnerRunner:
    """Drain joined examples into the online ring, snapshot-everything
    at deterministic boundaries, publish through the gate.

    ``estimator`` is any VowpalWabbit* estimator (its `online_learner`
    builds the ring; `state=` resumes one). ``source`` is a
    JsonlEventSource-shaped replayable source (`read` / `cursor` /
    `seek` / `commit`). All cadences count JOINED examples:

    - every `holdout_every`-th joined example -> the gate's window
      (never trained on);
    - `snapshot_every` joined examples -> `_commit_snapshot` (flush
      ring, persist {learner, joiner, cursor} atomically, fire the
      post-snapshot `join_boundary_hook` — exactly a preemption's
      timing, so `TrainingFaultInjector.arm(runner)` injects kills
      with the same determinism contract as the GBDT chunk kills);
    - `publish_every` joined examples -> the publish leg.

    `drain` (a PreemptionDrain) turns SIGTERM into a `Preempted` raise
    at the NEXT snapshot boundary — the snapshot is already durable, so
    the resumed run re-reads the event log from the committed cursor
    into the restored joiner: nothing lost, nothing double-applied."""

    SNAPSHOT_SCHEMA = 1

    def __init__(self, estimator, source, *, row_width: int,
                 store=None, joiner: Optional[RewardJoiner] = None,
                 horizon_s: float = 300.0,
                 snapshot_every: int = 2048, publish_every: int = 0,
                 holdout_every: int = 0, holdout_window: int = 256,
                 holdout_tolerance: float = 0.10,
                 publisher: Optional[ModelPublisher] = None,
                 submit_chunk: int = 256, read_batch: int = 1024,
                 drain=None, event_log=None, ndev: Optional[int] = None,
                 clock: Optional[Callable[[], float]] = None):
        if row_width < 1:
            raise ValueError("row_width must be >= 1")
        if snapshot_every < 1:
            raise ValueError("snapshot_every must be >= 1")
        if publish_every and publish_every % snapshot_every != 0:
            # flush points must be identical with and without a
            # publisher (the replay oracle runs without one) — see the
            # module docstring's determinism contract
            raise ValueError(
                f"publish_every ({publish_every}) must be a multiple of "
                f"snapshot_every ({snapshot_every})")
        self.estimator = estimator
        self.source = source
        self.store = store
        self.joiner = joiner or RewardJoiner(horizon_s=horizon_s)
        self.row_width = int(row_width)
        self.snapshot_every = int(snapshot_every)
        self.publish_every = int(publish_every)
        self.holdout_every = int(holdout_every)
        self.gate = HoldoutGate(row_width, window=holdout_window,
                                tolerance=holdout_tolerance) \
            if holdout_every else None
        self.publisher = publisher
        if publisher is not None and publisher.gate is None:
            publisher.gate = self.gate
        self.submit_chunk = int(submit_chunk)
        self.read_batch = int(read_batch)
        self.drain = drain
        self.event_log = event_log
        self.ndev = int(ndev) if ndev is not None else _local_device_count()
        self.clock = clock if clock is not None else _default_clock
        #: post-snapshot boundary hook — `TrainingFaultInjector.arm(self)`
        #: installs its kill here (fired AFTER the snapshot is durable)
        self._chunk_boundary_hook: Optional[Callable[[int, int], None]] = None
        self.counts: Dict[str, int] = {
            "joined": 0, "trained": 0, "held_out": 0, "snapshots": 0,
            "publishes": 0, "resumes": 0, "reshards": 0}
        self._staged: List[Dict[str, Any]] = []
        self._lags: List[float] = []
        self._snapshot_ordinal = 0
        self._ingest_cursor: Optional[Dict[str, Any]] = None
        self._ring = None
        self._restored_state = None
        self._resume()

    # ------------------------------------------------------------- wiring
    @property
    def ring(self):
        if self._ring is None:
            self._ring = self.estimator.online_learner(
                state=self._restored_state, width=self.row_width)
            self._restored_state = None
        return self._ring

    def arm(self, hook: Callable[[int, int], None]) -> "OnlineLearnerRunner":
        self._chunk_boundary_hook = hook
        return self

    def _log_event(self, event: str, **fields) -> None:
        if self.event_log is not None:
            try:
                self.event_log.append(event, **fields)
            except Exception:  # noqa: BLE001 - tracing must not alter the loop
                pass

    # ----------------------------------------------------------- hot path
    def step(self) -> int:
        """One loop iteration: read a batch of events, join, stage, and
        cross any cadence boundaries reached. Returns the number of
        events read (0 = source exhausted for now). HOT PATH: no host
        syncs here or in `_ingest_events`/`_apply_staged` — the syncs
        live in the designated commit points the boundary checks call
        into (`_commit_snapshot`/`_publish`), exactly the GBDT chunk
        loop's structure (AST-linted)."""
        events = self.source.read(max_records=self.read_batch)
        if events:
            self._ingest_events(events)
        if len(self._staged) >= self.submit_chunk:
            self._apply_staged()
        return len(events)

    def _ingest_events(self, events) -> None:
        """Join one batch of raw events; divert the deterministic
        holdout split; stage the rest for the ring. Boundary checks run
        PER JOINED EXAMPLE so snapshots/publishes land at exact
        ordinals regardless of how the source batched the reads (the
        determinism contract)."""
        for ev in events:
            joined = self.joiner.ingest(ev)
            if "_next_offset" in ev:
                # record-granular cursor: the snapshot must mark exactly
                # the events the joiner has absorbed, not the read batch
                self._ingest_cursor = {"offset": ev["_next_offset"]}
            if joined is None:
                continue
            self.counts["joined"] += 1
            if self.holdout_every and \
                    self.counts["joined"] % self.holdout_every == 0:
                self.counts["held_out"] += 1
                self.gate.add(joined)
            else:
                self._staged.append(joined)
            if self.counts["joined"] % self.snapshot_every == 0:
                self._apply_staged()
                self._commit_snapshot()
                if self.publisher is not None and self.publish_every \
                        and self.counts["joined"] % self.publish_every == 0:
                    self._publish()

    def _apply_staged(self) -> None:
        """Submit every staged example to the ring (the ring buffers
        into minibatches and ahead-dispatches; no sync here)."""
        if not self._staged:
            return
        staged, self._staged = self._staged, []
        idx, val, labels, weights = _coerce_rows(staged, self.row_width)
        self.ring.submit(idx, val, labels, weights)
        self.counts["trained"] += len(staged)
        now = self.clock()
        for ex in staged:
            self._lags.append(max(0.0, now - ex["reward_ts"]))

    # ------------------------------------------------------ commit points
    def _snapshot_payload(self) -> str:
        state = self.ring.state()
        return json.dumps({
            "schema": self.SNAPSHOT_SCHEMA,
            "learner_b64": base64.b64encode(
                state_to_bytes(state)).decode(),
            "learner_digest": state_digest(state),
            "joiner": self.joiner.snapshot_state(),
            "cursor": (self._ingest_cursor if self._ingest_cursor
                       is not None else self.source.cursor()),
            "joined": self.counts["joined"],
            "trained": self.counts["trained"],
            "held_out": self.counts["held_out"],
            "holdout_window": list(self.gate.window) if self.gate else [],
            "snapshot_ordinal": self._snapshot_ordinal,
        }, sort_keys=True)

    def _commit_snapshot(self) -> None:
        """DESIGNATED COMMIT POINT: flush the ring (zero-weight pad,
        bit-identical), read the carry back, persist {learner, joiner,
        cursor} as one atomic snapshot, then fire the post-snapshot
        boundary hook (preemption timing) and honor a drain request."""
        self.ring.flush()
        if self.store is not None:
            payload = self._snapshot_payload()
            rec = json.loads(payload)
            self.store.save(
                payload, step=self.counts["joined"], ndev=self.ndev,
                extra={"learner_digest": rec["learner_digest"],
                       "reward_cursor": rec["cursor"]})
            self.source.commit(rec["cursor"])
        self.counts["snapshots"] += 1
        ordinal = self._snapshot_ordinal
        self._snapshot_ordinal += 1
        self._flush_metrics()
        self._log_event("online_snapshot", ordinal=ordinal,
                        joined=self.counts["joined"])
        if self._chunk_boundary_hook is not None:
            self._chunk_boundary_hook(ordinal, self.counts["joined"])
        if self.drain is not None and self.drain.requested:
            raise Preempted(
                f"drain requested; snapshot at joined="
                f"{self.counts['joined']} is durable")

    def _flush_metrics(self) -> None:
        from ..observability.bridge import publish_online_apply
        lags, self._lags = self._lags, []
        publish_online_apply(
            0, reward_lag_s=lags,
            pending_keys=(self.joiner.pending_predictions
                          + self.joiner.pending_rewards))

    def _publish(self) -> None:
        """DESIGNATED COMMIT POINT: flush, finalize the carry into a
        candidate, gate it, publish, hand to the rollout."""
        from ..observability.bridge import publish_online_publish
        t0 = self.clock()
        self.ring.flush()
        state = self.ring.state()
        meta = {"joined": self.counts["joined"],
                "trained": self.counts["trained"],
                "ndev": self.ndev,
                "learner_digest": state_digest(state)}
        version = self.publisher.publish(state, meta)
        if version is not None:
            self.counts["publishes"] += 1
            publish_online_publish("published",
                                   swap_seconds=self.clock() - t0)
            self._log_event("online_publish", version=version,
                            joined=self.counts["joined"])

    # --------------------------------------------------------- run / drain
    def run(self, *, max_steps: Optional[int] = None,
            idle_limit: int = 1) -> Dict[str, int]:
        """Drive `step` until the source runs dry `idle_limit` times in
        a row (or `max_steps`). Returns the counts dict."""
        idle = 0
        steps = 0
        while (max_steps is None or steps < max_steps) \
                and idle < idle_limit:
            n = self.step()
            steps += 1
            idle = 0 if n else idle + 1
        return dict(self.counts)

    def finalize(self):
        """Drain everything staged, flush, and return (state, digest) —
        the number the parity proof compares."""
        self._apply_staged()
        self.ring.flush()
        state = self.ring.state()
        return state, state_digest(state)

    # -------------------------------------------------------------- resume
    def _resume(self) -> None:
        """Restore {learner, joiner, cursor} from the newest durable
        snapshot (digest-verified by the store, counted fallback on
        corruption). A resume at a different device count than the
        snapshot's is counted as a reshard — the VW carry is unsharded
        [F] state, so the resumed digest is unchanged (proved at ndev
        {1,2} in tests)."""
        if self.store is None:
            return
        restored = self.store.restore()
        if restored is None:
            return
        payload, manifest = restored
        rec = json.loads(payload)
        if rec.get("schema") != self.SNAPSHOT_SCHEMA:
            raise ValueError(
                f"online snapshot schema {rec.get('schema')!r} != "
                f"{self.SNAPSHOT_SCHEMA}")
        self._restored_state = state_from_bytes(
            base64.b64decode(rec["learner_b64"]))
        if state_digest(self._restored_state) != rec["learner_digest"]:
            raise ValueError("restored learner digest mismatch "
                             "(snapshot payload inconsistent)")
        self.joiner.restore_state(rec["joiner"])
        self.source.seek(rec["cursor"])
        self._ingest_cursor = dict(rec["cursor"])
        self.counts["joined"] = int(rec["joined"])
        self.counts["trained"] = int(rec["trained"])
        self.counts["held_out"] = int(rec["held_out"])
        self.counts["resumes"] += 1
        if self.gate is not None:
            for ex in rec.get("holdout_window", []):
                self.gate.add(ex)
        self._snapshot_ordinal = int(rec.get("snapshot_ordinal", 0))
        if int(manifest.get("ndev", self.ndev)) != self.ndev:
            self.counts["reshards"] += 1
        self._log_event("online_resume", joined=self.counts["joined"],
                        ndev=self.ndev)


def _default_clock() -> float:
    import time
    return time.perf_counter()


def offline_replay(estimator, source, *, row_width: int,
                   **runner_kw) -> str:
    """The parity oracle: run the SAME event log through a fresh,
    uninterrupted runner (no store, no publisher — cadences identical
    because they are joined-ordinal keyed) and return the final learner
    digest. An interrupted+resumed run over the same log must match it
    bit for bit."""
    runner = OnlineLearnerRunner(
        estimator, source, row_width=row_width, store=None,
        publisher=None, **runner_kw)
    runner.run(idle_limit=2)
    _, digest = runner.finalize()
    return digest
