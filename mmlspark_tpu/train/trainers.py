"""TrainClassifier / TrainRegressor — auto-featurizing trainers.

Reference: train/AutoTrainer.scala:12 (featurize + inner SparkML learner),
train/TrainClassifier.scala:53-374 (label reindexing via ValueIndexer, per-algo
handling, levels stored on the model, scores/scored_probabilities/scored_labels
output convention — TrainedClassifierModel :276), train/TrainRegressor.scala.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model, Transformer
from ..featurize.featurize import Featurize
from ..featurize.indexers import ValueIndexer

# assembled-features hash bits: 2^18 default, 2^12 for tree learners
# (featurize/Featurize.scala:17-20)
FEATURES_DEFAULT = 1 << 18
FEATURES_TREE = 1 << 12


def _is_tree_learner(est) -> bool:
    name = type(est).__name__
    return "LightGBM" in name or "GBT" in name or "Forest" in name


class AutoTrainer(Estimator, _p.HasLabelCol, _p.HasFeaturesCol):
    """Shared surface: featurize all non-label columns into one vector, then
    fit the inner learner on it (train/AutoTrainer.scala:12)."""

    model = _p.Param("model", "inner learner estimator", None, complex=True)
    numFeatures = _p.Param(
        "numFeatures", "hash-space size for string featurization; 0 = auto "
        "(2^18, or 2^12 for tree learners)", 0, int)

    def __init__(self, model: Optional[Estimator] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)

    def _default_learner(self) -> Estimator:
        raise NotImplementedError

    def _featurizer(self, df: DataFrame, label_col: str) -> "Model":
        inner = self.get("model") or self._default_learner()
        nf = self.get("numFeatures")
        if not nf:
            nf = FEATURES_TREE if _is_tree_learner(inner) else FEATURES_DEFAULT
        cols = [c for c in df.columns if c != label_col]
        feat = Featurize(inputCols=cols, outputCol=self.get("featuresCol"),
                         numberOfFeatures=nf)
        return feat.fit(df)


class TrainClassifier(AutoTrainer):
    """Reindex labels -> featurize -> fit inner classifier.

    Reference: train/TrainClassifier.scala:53-374."""

    reindexLabel = _p.Param("reindexLabel",
                            "reindex label values to contiguous ints", True,
                            bool)

    def _default_learner(self) -> Estimator:
        from ..models.classic import LogisticRegression
        return LogisticRegression()

    def _fit(self, df: DataFrame) -> "TrainedClassifierModel":
        label_col = self.get("labelCol")
        levels: Optional[List[Any]] = None
        work = df
        if self.get("reindexLabel"):
            indexer = ValueIndexer(inputCol=label_col,
                                   outputCol=label_col).fit(df)
            levels = indexer.get("levels")
            work = indexer.transform(df)
        feat_model = self._featurizer(work, label_col)
        feats = feat_model.transform(work)
        inner = (self.get("model") or self._default_learner()).copy({
            "labelCol": label_col,
            "featuresCol": self.get("featuresCol")})
        fitted = inner.fit(feats)
        model = TrainedClassifierModel(
            featurizer=feat_model, inner_model=fitted, levels=levels)
        model.set("labelCol", label_col)
        model.set("featuresCol", self.get("featuresCol"))
        return model


class TrainedClassifierModel(Model, _p.HasLabelCol, _p.HasFeaturesCol):
    """Output convention (TrainClassifier.scala:276): `scores`,
    `scored_probabilities`, `scored_labels` (decoded back through levels)."""

    featurizer = _p.Param("featurizer", "fitted featurize model", None,
                          complex=True)
    innerModel = _p.Param("innerModel", "fitted inner classifier", None,
                          complex=True)
    levels = _p.Param("levels", "original label levels", None, complex=True)

    def __init__(self, featurizer=None, inner_model=None, levels=None, **kw):
        super().__init__(**kw)
        if featurizer is not None:
            self._set(featurizer=featurizer, innerModel=inner_model,
                      levels=levels)

    def transform(self, df: DataFrame) -> DataFrame:
        feats = self.get("featurizer").transform(df)
        scored = self.get("innerModel").transform(feats)
        inner = self.get("innerModel")
        out = df
        raw_col = (inner.get("rawPredictionCol")
                   if inner.has_param("rawPredictionCol") else None)
        if raw_col and raw_col in scored:
            out = out.with_column("scores", scored[raw_col])
        prob_col = (inner.get("probabilityCol")
                    if inner.has_param("probabilityCol") else None)
        levels = self.get("levels")
        if prob_col and prob_col in scored:
            # column ordering metadata lets stats stages index probabilities
            # by the TRAINING levels (SparkSchema.scala score-column metadata)
            out = out.with_column(
                "scored_probabilities", scored[prob_col],
                metadata={"levels": list(levels)} if levels is not None
                else None)
        preds = np.asarray(scored[inner.get("predictionCol")], np.float64)
        if levels is not None:
            decoded = np.empty(len(preds), dtype=object)
            for i, p in enumerate(preds):
                decoded[i] = levels[int(p)] if 0 <= int(p) < len(levels) else None
            arr = np.asarray(decoded)
            try:  # numeric levels decode back to a numeric column
                arr = decoded.astype(np.float64)
            except (TypeError, ValueError):
                arr = decoded
            out = out.with_column("scored_labels", arr)
        else:
            out = out.with_column("scored_labels", preds)
        return out


class TrainRegressor(AutoTrainer):
    """Reference: train/TrainRegressor.scala."""

    def _default_learner(self) -> Estimator:
        from ..models.classic import LinearRegression
        return LinearRegression()

    def _fit(self, df: DataFrame) -> "TrainedRegressorModel":
        label_col = self.get("labelCol")
        feat_model = self._featurizer(df, label_col)
        feats = feat_model.transform(df)
        inner = (self.get("model") or self._default_learner()).copy({
            "labelCol": label_col,
            "featuresCol": self.get("featuresCol")})
        fitted = inner.fit(feats)
        model = TrainedRegressorModel(featurizer=feat_model,
                                      inner_model=fitted)
        model.set("labelCol", label_col)
        model.set("featuresCol", self.get("featuresCol"))
        return model


class TrainedRegressorModel(Model, _p.HasLabelCol, _p.HasFeaturesCol):
    featurizer = _p.Param("featurizer", "fitted featurize model", None,
                          complex=True)
    innerModel = _p.Param("innerModel", "fitted inner regressor", None,
                          complex=True)

    def __init__(self, featurizer=None, inner_model=None, **kw):
        super().__init__(**kw)
        if featurizer is not None:
            self._set(featurizer=featurizer, innerModel=inner_model)

    def transform(self, df: DataFrame) -> DataFrame:
        feats = self.get("featurizer").transform(df)
        scored = self.get("innerModel").transform(feats)
        inner = self.get("innerModel")
        return df.with_column("scores",
                              scored[inner.get("predictionCol")])
