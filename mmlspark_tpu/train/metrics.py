"""Metric constants + computations.

Reference: core/metrics/MetricConstants.scala:7-97 (metric name enumeration),
core/metrics/MetricUtils.scala, and the metric math inside
train/ComputeModelStatistics.scala:56-400. Host-side numpy: metric reduction is
cheap compared to training and keeps results exact/deterministic.
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np


class MetricConstants:
    """Metric names (MetricConstants.scala:7-97)."""
    ACCURACY = "accuracy"
    PRECISION = "precision"
    RECALL = "recall"
    AUC = "AUC"
    F1 = "f1"
    MSE = "mse"
    RMSE = "rmse"
    R2 = "R^2"
    MAE = "mean_absolute_error"
    ALL = "all"

    CLASSIFICATION_METRICS = [ACCURACY, PRECISION, RECALL, AUC]
    REGRESSION_METRICS = [MSE, RMSE, R2, MAE]


def index_label_pred(label_raw: np.ndarray, pred_raw: np.ndarray):
    """Coerce label/prediction columns to numeric class indices. Non-numeric
    (string/categorical — e.g. TrainClassifier's decoded scored_labels) are
    indexed jointly over their sorted observed levels, the way the reference
    recovers levels from column metadata."""
    if label_raw.dtype == object or pred_raw.dtype == object:
        levels = sorted(set(label_raw.tolist()) | set(pred_raw.tolist()),
                        key=str)
        lookup = {v: i for i, v in enumerate(levels)}
        labels = np.array([lookup[v] for v in label_raw], np.float64)
        preds = np.array([lookup[v] for v in pred_raw], np.float64)
        return labels, preds
    return (np.asarray(label_raw, np.float64),
            np.asarray(pred_raw, np.float64))


def auc_score(labels: np.ndarray, scores: np.ndarray) -> float:
    """Area under the ROC curve via the Mann-Whitney rank statistic
    (equivalent to the trapezoid ROC integral the reference computes through
    BinaryClassificationMetrics)."""
    labels = np.asarray(labels, np.float64)
    scores = np.asarray(scores, np.float64)
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    # average ranks with tie-groups, fully vectorized: for each distinct
    # score the average rank is the mean of its occupied rank positions
    uniq, inv, counts = np.unique(scores, return_inverse=True,
                                  return_counts=True)
    ends = np.cumsum(counts).astype(np.float64)        # last rank per group
    avg_rank = ends - (counts - 1) / 2.0               # mean of the run
    ranks = avg_rank[inv]
    rank_sum = ranks[pos].sum()
    return float((rank_sum - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def confusion_matrix(labels: np.ndarray, preds: np.ndarray,
                     num_class: int) -> np.ndarray:
    labels = np.asarray(labels, np.int64)
    preds = np.asarray(preds, np.int64)
    if labels.min(initial=0) < 0 or preds.min(initial=0) < 0:
        raise ValueError(
            "labels/predictions must be non-negative class indices "
            "(got negative values — reindex e.g. -1/+1 labels first)")
    cm = np.zeros((num_class, num_class), np.int64)
    np.add.at(cm, (labels, preds), 1)
    return cm


def classification_metrics(labels: np.ndarray, preds: np.ndarray,
                           scores: np.ndarray = None) -> Dict[str, float]:
    """Binary metrics (ComputeModelStatistics.scala binary path): accuracy,
    precision/recall of the positive class, AUC from scores."""
    labels = np.asarray(labels, np.int64)
    preds = np.asarray(preds, np.int64)
    cm = confusion_matrix(labels, preds, 2)
    tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
    out = {
        MetricConstants.ACCURACY: float((tp + tn) / max(cm.sum(), 1)),
        MetricConstants.PRECISION: float(tp / max(tp + fp, 1)),
        MetricConstants.RECALL: float(tp / max(tp + fn, 1)),
    }
    p, r = out[MetricConstants.PRECISION], out[MetricConstants.RECALL]
    out[MetricConstants.F1] = 2 * p * r / max(p + r, 1e-12)
    if scores is not None:
        out[MetricConstants.AUC] = auc_score(labels, scores)
    return out


def multiclass_metrics(labels: np.ndarray, preds: np.ndarray,
                       num_class: int) -> Dict[str, float]:
    """Macro-averaged multiclass metrics (ComputeModelStatistics.scala:323-370)."""
    cm = confusion_matrix(labels, preds, num_class)
    tp = np.diag(cm).astype(np.float64)
    support = cm.sum(axis=1).astype(np.float64)
    predicted = cm.sum(axis=0).astype(np.float64)
    prec = np.where(predicted > 0, tp / np.maximum(predicted, 1), 0.0)
    rec = np.where(support > 0, tp / np.maximum(support, 1), 0.0)
    live = support > 0
    macro_p = float(prec[live].mean()) if live.any() else 0.0
    macro_r = float(rec[live].mean()) if live.any() else 0.0
    return {
        MetricConstants.ACCURACY: float(tp.sum() / max(cm.sum(), 1)),
        "macro_precision": macro_p,
        "macro_recall": macro_r,
        "micro_precision": float(tp.sum() / max(predicted.sum(), 1)),
        "micro_recall": float(tp.sum() / max(support.sum(), 1)),
        # binary-named aliases resolve to the macro average so a requested
        # 'precision'/'recall'/'f1' metric works on multiclass problems too
        MetricConstants.PRECISION: macro_p,
        MetricConstants.RECALL: macro_r,
        MetricConstants.F1: (2 * macro_p * macro_r / max(macro_p + macro_r,
                                                         1e-12)),
    }


def regression_metrics(labels: np.ndarray, preds: np.ndarray
                       ) -> Dict[str, float]:
    labels = np.asarray(labels, np.float64)
    preds = np.asarray(preds, np.float64)
    err = preds - labels
    mse = float(np.mean(err ** 2))
    ss_tot = float(np.sum((labels - labels.mean()) ** 2))
    return {
        MetricConstants.MSE: mse,
        MetricConstants.RMSE: float(np.sqrt(mse)),
        MetricConstants.R2: (1.0 - float(np.sum(err ** 2)) / ss_tot
                             if ss_tot > 0 else 0.0),
        MetricConstants.MAE: float(np.mean(np.abs(err))),
    }
