"""ComputeModelStatistics / ComputePerInstanceStatistics.

Reference: train/ComputeModelStatistics.scala:56-400 — confusion matrix,
accuracy/precision/recall, AUC (binary), macro/micro multiclass metrics,
regression MSE/RMSE/R2/MAE — emitted as a one-row metrics DataFrame; and
train/ComputePerInstanceStatistics.scala:42 — per-row log-loss / squared error.
Column-name conventions follow the scored-DataFrame convention of
core/schema/SparkSchema.scala (scores / scored_probabilities / scored_labels).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Transformer
from .metrics import (MetricConstants, classification_metrics,
                      confusion_matrix, index_label_pred, multiclass_metrics,
                      regression_metrics)


def _detect_scored_cols(df: DataFrame):
    """Find scored columns by convention (SparkSchema.scala score-column
    metadata): scored_labels/prediction, scored_probabilities/probability."""
    pred = next((c for c in ("scored_labels", "prediction") if c in df), None)
    prob = next((c for c in ("scored_probabilities", "probability")
                 if c in df), None)
    return pred, prob


class ComputeModelStatistics(Transformer, _p.HasLabelCol):
    evaluationMetric = _p.Param(
        "evaluationMetric",
        "classification | regression | all (auto-detected when unset)", "all")
    scoredLabelsCol = _p.Param("scoredLabelsCol",
                               "predicted label column", None)
    scoresCol = _p.Param("scoresCol", "raw score / probability column", None)

    def transform(self, df: DataFrame) -> DataFrame:
        pred_col, prob_col = _detect_scored_cols(df)
        if self.get("scoredLabelsCol"):
            pred_col = self.get("scoredLabelsCol")
        if self.get("scoresCol"):
            prob_col = self.get("scoresCol")
        if pred_col is None:
            raise ValueError("no prediction column found "
                             "(scored_labels/prediction)")
        labels, preds = index_label_pred(df[self.get("labelCol")],
                                         df[pred_col])

        kind = self.get("evaluationMetric")
        if kind in ("all", None):
            is_int = np.allclose(labels, np.round(labels))
            kind = ("classification"
                    if is_int and len(np.unique(labels)) <= 20
                    else "regression")

        if kind == "regression":
            return DataFrame({k: np.array([v]) for k, v in
                              regression_metrics(labels, preds).items()})

        num_class = int(max(labels.max(), preds.max())) + 1
        if num_class <= 2:
            scores = None
            if prob_col is not None:
                probs = df[prob_col]
                scores = (np.asarray(probs, np.float64)[:, 1]
                          if np.asarray(probs).ndim == 2 else
                          np.asarray(probs, np.float64))
            m = classification_metrics(labels, preds, scores)
        else:
            m = multiclass_metrics(labels, preds, num_class)
        cm = confusion_matrix(labels.astype(np.int64),
                              preds.astype(np.int64), max(num_class, 2))
        out = {k: np.array([v]) for k, v in m.items()}
        cm_col = np.empty(1, dtype=object)
        cm_col[0] = cm
        out["confusion_matrix"] = cm_col
        return DataFrame(out)


class ComputePerInstanceStatistics(Transformer, _p.HasLabelCol):
    """Per-row log-loss (classification, from scored probabilities) or squared
    / absolute error (regression). Reference: ComputePerInstanceStatistics.scala:42."""

    evaluationMetric = _p.Param(
        "evaluationMetric", "classification | regression | all", "all")

    def transform(self, df: DataFrame) -> DataFrame:
        pred_col, prob_col = _detect_scored_cols(df)
        kind = self.get("evaluationMetric")
        if kind in ("all", None):
            kind = ("classification" if prob_col is not None else "regression")
        if kind == "classification":
            label_raw = df[self.get("labelCol")]
            levels = (df.metadata(prob_col) or {}).get("levels")
            probs = np.asarray(df[prob_col], np.float64)
            if probs.ndim == 1:
                probs = np.stack([1 - probs, probs], axis=1)
            if levels is not None:
                # index by the MODEL's training levels so label i matches
                # probability column i (levels metadata set by
                # TrainedClassifierModel.transform); applies to string AND
                # non-contiguous numeric labels alike
                lookup = {v: i for i, v in enumerate(levels)}
                labels = np.array([lookup.get(v, -1) for v in label_raw],
                                  np.float64)
                if (labels < 0).any():
                    raise ValueError("labels outside the model's training "
                                     "levels")
            else:
                labels, _ = index_label_pred(label_raw,
                                             df[pred_col] if pred_col
                                             else label_raw)
                if labels.max(initial=0) >= probs.shape[1]:
                    # non-contiguous numeric labels without metadata:
                    # reindex by sorted observed values
                    uniq = np.unique(labels)
                    remap = {v: i for i, v in enumerate(uniq)}
                    labels = np.array([remap[v] for v in labels], np.float64)
            idx = labels.astype(np.int64)
            p_true = np.clip(probs[np.arange(len(labels)), idx], 1e-15, 1.0)
            return df.with_column("log_loss", -np.log(p_true))
        labels = np.asarray(df[self.get("labelCol")], np.float64)
        preds = np.asarray(df[pred_col], np.float64)
        err = preds - labels
        return (df.with_column("squared_error", err ** 2)
                  .with_column("absolute_error", np.abs(err)))
