"""Compilation layer: kill cold start.

- ``cache`` — the ``cached_jit`` in-memory wrapper registry + persistent
  on-disk XLA cache management + cache_stats telemetry hook.
- ``aot`` — ``jax.export`` artifact store (serialize / digest-verified
  deserialize-or-fall-back) for serving-critical predict programs.

See docs/SERVING.md ("Cold start") and docs/RESILIENCE.md
(resume-to-first-chunk) for the measured before/after.
"""

from .aot import AOT_SCHEMA_VERSION, AOTStore
from .cache import (CachedFunction, cache_stats, cached_jit,
                    clear_memory_cache, configure_persistent_cache,
                    persistent_cache_dir)

__all__ = [
    "AOT_SCHEMA_VERSION", "AOTStore", "CachedFunction", "cache_stats",
    "cached_jit", "clear_memory_cache", "configure_persistent_cache",
    "persistent_cache_dir",
]
