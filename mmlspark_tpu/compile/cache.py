"""Compilation caching: one `cached_jit` front door + the persistent XLA cache.

Recompiles are the fleet's dominant recovery cost (ROADMAP item 3: a hung
ResNet-50 compile wedged the pool for a round; bench budgets ~22 min of
bring-up), so every hot entry point acquires its jitted callable here instead
of calling ``jax.jit`` ad hoc. Two layers:

- **In-memory (process) layer** — ``cached_jit(fn, key=...)`` memoizes the
  *wrapper object* on an explicit static-config key plus the backend
  fingerprint, so two estimator instances with the same static config share
  ONE executable instead of re-tracing per instance (the round-11 churn:
  ``DNNModel``'s per-instance ``_jitted`` dict, the transformer models'
  per-instance ``_fwd_cache``, and per-fit ``jax.jit(train)`` closures in VW).
  jax.jit's own trace cache handles shape/dtype specialization below that.

- **Persistent layer** — JAX's on-disk XLA compilation cache
  (``jax_compilation_cache_dir``), enabled and managed by
  ``configure_persistent_cache``. Keys there are XLA's own (backend +
  topology + HLO + compile options), which subsume the (backend/topology,
  shapes, dtypes, donation/sharding) tuple; a freshly scheduled or
  elastic-resumed worker re-deserializes executables instead of recompiling.

Both layers feed hit/miss/compile-second counters into the metrics registry
(``cache_stats`` is the snapshot hook; bench embeds it per emitted JSON).

The Flare argument (arxiv 1703.08219) for ahead-of-time native compilation is
exactly this layer; the reference ships pre-built model artifacts to executors
(ModelDownloader/CNTKModel) where we ship serialized executables (see
``compile/aot.py``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

__all__ = [
    "CachedFunction", "cached_jit", "cache_stats", "clear_memory_cache",
    "configure_persistent_cache", "persistent_cache_dir",
]

_LOCK = threading.RLock()
_REGISTRY: Dict[Any, "CachedFunction"] = {}

# persistent-layer state: configured dir (None until configure) and the
# monitoring-listener event tallies (XLA cache hits are only observable
# through jax's monitoring events)
_PERSISTENT: Dict[str, Any] = {"dir": None, "listeners": False,
                               "hits": 0, "requests": 0,
                               "retrieval_seconds": 0.0}

#: env switches — MMLSPARK_COMPILE_CACHE=0 disables the persistent layer
#: (the in-memory layer is always on; it has no failure mode), and
#: MMLSPARK_COMPILE_CACHE_DIR overrides the on-disk location.
ENV_ENABLE = "MMLSPARK_COMPILE_CACHE"
ENV_DIR = "MMLSPARK_COMPILE_CACHE_DIR"
_DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                            "mmlspark_tpu", "xla-cache")


def _metrics():
    """Registry handles, resolved lazily so importing compile/ never forces
    the observability module (and tests can swap the process registry)."""
    from ..observability import get_registry
    return get_registry()


def _count(layer: str, event: str, entry_point: str) -> None:
    try:
        _metrics().counter(
            "compile_cache_events_total",
            "compilation cache lookups by layer (memory|persistent) and "
            "event (hit|miss)",
            {"layer": layer, "event": event, "entry_point": entry_point},
        ).inc()
    except Exception:
        pass  # telemetry must never break a fit or a serve


def _add_compile_seconds(entry_point: str, secs: float) -> None:
    try:
        _metrics().counter(
            "compile_seconds_total",
            "wall seconds spent inside first-call trace+compile per entry "
            "point (new argument signatures only)",
            {"entry_point": entry_point}).inc(secs)
    except Exception:
        pass


def _backend_fingerprint() -> Tuple[str, int]:
    """(platform, visible device count) — the topology part of the cache
    key. XLA's own persistent key covers the full topology; this keeps the
    in-memory layer from handing a 1-device executable to an 8-device mesh
    config (mesh extent is also in every caller's explicit key)."""
    try:
        return (jax.default_backend(), jax.device_count())
    except Exception:  # backend not initializable (e.g. doc builds)
        return ("uninitialized", 0)


def _leaf_sig(leaf: Any) -> Any:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    try:
        hash(leaf)
        return ("v", leaf)
    except TypeError:
        return ("t", type(leaf).__name__)


class CachedFunction:
    """A shared jitted callable with hit/miss/compile-seconds accounting.

    The first call with a previously unseen argument signature (pytree
    structure + leaf shapes/dtypes + static values) is counted as a
    **memory miss** and its wall time booked to ``compile_seconds_total`` —
    that call pays trace+compile (or a persistent-cache deserialize).
    Every later call with a seen signature is a **memory hit** and goes
    straight to jax.jit's executable lookup.
    """

    __slots__ = ("name", "key", "_fn", "_jitted", "_signatures", "_lock")

    def __init__(self, fn: Callable, name: str, key: Any,
                 static_argnames=(), donate_argnums=(), **jit_kwargs):
        self.name = name
        self.key = key
        self._fn = fn
        self._jitted = jax.jit(fn, static_argnames=static_argnames,
                               donate_argnums=donate_argnums, **jit_kwargs)
        self._signatures: set = set()
        self._lock = threading.Lock()

    def _signature(self, args, kwargs) -> Any:
        leaves, treedef = jax.tree.flatten((args, kwargs))
        return (treedef, tuple(_leaf_sig(l) for l in leaves))

    def __call__(self, *args, **kwargs):
        sig = self._signature(args, kwargs)
        with self._lock:
            seen = sig in self._signatures
            if not seen:
                self._signatures.add(sig)
        if seen:
            _count("memory", "hit", self.name)
            return self._jitted(*args, **kwargs)
        _count("memory", "miss", self.name)
        t0 = time.perf_counter()
        try:
            return self._jitted(*args, **kwargs)
        finally:
            _add_compile_seconds(self.name, time.perf_counter() - t0)

    # jit-object passthroughs used by AOT export and tests
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    @property
    def jitted(self):
        return self._jitted

    @property
    def signatures_seen(self) -> int:
        return len(self._signatures)

    def __repr__(self) -> str:
        return (f"CachedFunction({self.name!r}, "
                f"signatures={len(self._signatures)})")


def cached_jit(fn: Callable, *, key: Any, name: Optional[str] = None,
               static_argnames=(), donate_argnums=(),
               **jit_kwargs) -> CachedFunction:
    """The one front door for jitted callables on hot fit/serve paths.

    ``key`` must be a hashable value that FULLY determines the traced
    computation modulo traced arguments (static config, mesh extent,
    donation/sharding choice — anything baked into the closure). Two calls
    with equal keys share one ``CachedFunction`` (the first caller's ``fn``
    wins), so identical configs across estimator instances — or across a
    preempt→resume pair in one process — share one executable. The backend
    fingerprint (platform, device count) is appended automatically.

    Enables the persistent on-disk layer as a side effect (first call only;
    no-op when disabled via ``MMLSPARK_COMPILE_CACHE=0``).
    """
    name = name or getattr(fn, "__name__", "anonymous")
    full_key = (name, key, static_argnames, donate_argnums,
                _backend_fingerprint())
    with _LOCK:
        entry = _REGISTRY.get(full_key)
        if entry is not None:
            _count("memory", "wrapper_hit", name)
            return entry
        configure_persistent_cache()
        entry = CachedFunction(fn, name, full_key,
                               static_argnames=static_argnames,
                               donate_argnums=donate_argnums, **jit_kwargs)
        _REGISTRY[full_key] = entry
        try:
            _metrics().gauge(
                "compile_cache_entries",
                "cached_jit wrapper objects resident in-process"
            ).set(float(len(_REGISTRY)))
        except Exception:
            pass
        return entry


# --------------------------------------------------------- persistent layer

def _on_cache_event(event: str, **kw) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _PERSISTENT["hits"] += 1
        _count("persistent", "hit", "_xla")
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        _PERSISTENT["requests"] += 1


def _on_cache_duration(event: str, duration: float, **kw) -> None:
    if "compilation_cache" in event and "retrieval" in event:
        _PERSISTENT["retrieval_seconds"] += duration


def configure_persistent_cache(cache_dir: Optional[str] = None,
                               min_compile_secs: Optional[float] = None,
                               ) -> Optional[str]:
    """Enable JAX's on-disk compilation cache (idempotent).

    Resolution order: explicit ``cache_dir`` > ``MMLSPARK_COMPILE_CACHE_DIR``
    > ``~/.cache/mmlspark_tpu/xla-cache``. Returns the active directory, or
    None when disabled (``MMLSPARK_COMPILE_CACHE=0``). The default
    min-compile-time threshold is 0 s — the fleet's pain is many medium
    compiles at bring-up, not a single giant one, so everything is cached
    (override via MMLSPARK_COMPILE_CACHE_MIN_SECS).
    """
    if os.environ.get(ENV_ENABLE, "1").lower() in ("0", "off", "false"):
        return None
    with _LOCK:
        if _PERSISTENT["dir"] is not None and cache_dir is None:
            return _PERSISTENT["dir"]
        path = (cache_dir or os.environ.get(ENV_DIR) or _DEFAULT_DIR)
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            if min_compile_secs is None:
                min_compile_secs = float(os.environ.get(
                    "MMLSPARK_COMPILE_CACHE_MIN_SECS", "0"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              min_compile_secs)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:
            return None  # cache is an optimization, never a crash
        try:
            # jax initializes its cache object AT MOST ONCE, at the first
            # compile of the process; if that compile ran before this
            # configure call — a jnp.asarray during model load is enough —
            # the cache is latched as "initialized, no backing store"
            # (_cache_initialized=True, _cache=None) and every later
            # read/write silently no-ops. Un-latch so late enablement
            # works; reset_cache() is jax's own back-to-pristine hook.
            from jax._src import compilation_cache as _cc
            if getattr(_cc, "_cache_initialized", False) \
                    and getattr(_cc, "_cache", None) is None:
                _cc.reset_cache()
        except Exception:
            pass
        if not _PERSISTENT["listeners"]:
            try:
                from jax._src import monitoring
                monitoring.register_event_listener(_on_cache_event)
                monitoring.register_event_duration_secs_listener(
                    _on_cache_duration)
                _PERSISTENT["listeners"] = True
            except Exception:
                pass  # stats degrade, caching still works
        _PERSISTENT["dir"] = path
        return path


def persistent_cache_dir() -> Optional[str]:
    return _PERSISTENT["dir"]


import contextlib


@contextlib.contextmanager
def uncached_compile():
    """Force compiles inside the block to bypass the persistent cache.

    An executable RETRIEVED from the persistent cache serializes without
    its symbol payload on XLA:CPU — exporting it produces an artifact that
    fails to deserialize ("Symbols not found"). AOT export therefore
    compiles from scratch inside this context. Not thread-safe (it resets
    jax's process-wide cache latch); export is an offline publish step.
    """
    from jax._src import compilation_cache as _cc
    old_dir = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        _cc.reset_cache()
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
        _cc.reset_cache()


# ----------------------------------------------------------------- snapshot

def cache_stats() -> Dict[str, Any]:
    """Snapshot for bench JSON / measure scripts: both layers + AOT."""
    reg = _metrics()
    snap = {"entries": len(_REGISTRY),
            "persistent_dir": _PERSISTENT["dir"],
            "persistent_hits": _PERSISTENT["hits"],
            "persistent_requests": _PERSISTENT["requests"],
            "persistent_retrieval_seconds":
                round(_PERSISTENT["retrieval_seconds"], 4)}
    try:
        fam = reg.snapshot().get("compile_cache_events_total", {})
        mem_hit = mem_miss = 0.0
        per_entry: Dict[str, Dict[str, float]] = {}
        for row in fam.get("series", ()):
            labels, v = row.get("labels", {}), float(row.get("value", 0))
            if labels.get("layer") != "memory":
                continue
            ev = labels.get("event", "")
            if ev == "hit":
                mem_hit += v
            elif ev == "miss":
                mem_miss += v
            if ev in ("hit", "miss"):
                ep = per_entry.setdefault(labels.get("entry_point", "?"),
                                          {"hit": 0.0, "miss": 0.0})
                ep[ev] += v
        snap["memory_hits"] = mem_hit
        snap["memory_misses"] = mem_miss
        snap["per_entry_point"] = per_entry
    except Exception:
        pass
    try:
        snap["compile_seconds_total"] = reg.total("compile_seconds_total")
    except Exception:
        pass
    try:
        snap["aot_fallbacks_total"] = reg.total("compile_aot_fallback_total")
        snap["aot_loads_ok_total"] = reg.total("compile_aot_load_ok_total")
    except Exception:
        pass
    return snap


_CLEAR_HOOKS: list = []


def on_clear(fn: Callable[[], None]) -> Callable[[], None]:
    """Register a callback run by clear_memory_cache — modules that memoize
    cached_jit wrappers themselves (e.g. the lru-cached GBDT program
    factories) register their cache_clear here so one clear drops BOTH
    layers; a stale outer memo would otherwise keep handing back wrappers
    whose jit executables a jax.clear_caches() already destroyed."""
    _CLEAR_HOOKS.append(fn)
    return fn


def clear_memory_cache() -> None:
    """Drop every cached wrapper (tests; pairs with jax.clear_caches())."""
    with _LOCK:
        _REGISTRY.clear()
        for fn in _CLEAR_HOOKS:
            try:
                fn()
            except Exception:
                pass
