"""AOT-exported executables: serialize serving-critical programs to disk.

Two artifact layers per entry, stored beside model checkpoints as manifest
entries — the executable analogue of the reference's pre-built model
artifacts shipped to executors (ModelDownloader/CNTKModel), and the layer
Flare argues for with ahead-of-time native compilation (arxiv 1703.08219):

- ``<name>.xexec`` — a PRE-COMPILED XLA executable
  (``jax.experimental.serialize_executable``): load + run, zero tracing,
  zero compilation. Strictly pinned to (jax version, platform, device
  kind, device count) — any skew is a counted fallback.
- ``<name>.jaxexport`` — the portable ``jax.export`` layer (versioned
  StableHLO + calling convention): skips Python tracing; its XLA compile
  resolves through the persistent cache (``compile/cache.py``).

The loader tries compiled -> exported -> (caller's) fresh JIT.

Discipline (inherited from the PR 10 checkpoint layer):

- every write goes through ``resilience.elastic.atomic_write_bytes`` /
  ``atomic_write_text`` — a preempted export can never leave a torn artifact;
- every artifact carries a sha256 digest in ``MANIFEST.json``; the loader
  verifies it before deserializing (the ``.xexec`` pickle in particular is
  only ever fed bytes that hash to the manifest digest — same trust domain
  as the model-weight files beside it);
- every load failure (missing, truncated/digest, schema or jax version skew,
  platform or device-count/kind mismatch, aval mismatch, deserialize error)
  is a COUNTED, logged fallback — never a crash
  (``compile_aot_fallback_total{reason}``).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
from typing import Any, Dict, Optional, Sequence

import jax

from ..resilience.elastic import atomic_write_bytes, atomic_write_text

__all__ = ["AOT_SCHEMA_VERSION", "AOTStore", "aval_strs", "count_fallback",
           "load_serving_callable"]

log = logging.getLogger(__name__)

AOT_SCHEMA_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
ARTIFACT_SUFFIX = ".jaxexport"
COMPILED_SUFFIX = ".xexec"


def _registry():
    from ..observability import get_registry
    return get_registry()


def _count_fallback(reason: str, name: str) -> None:
    log.warning("AOT artifact %r unusable (%s); falling back to JIT",
                name, reason)
    try:
        _registry().counter(
            "compile_aot_fallback_total",
            "AOT artifact loads that fell back to fresh JIT, by reason",
            {"reason": reason}).inc()
    except Exception:
        pass


#: public alias — callers that do their own late validation (e.g. a booster
#: comparing exported avals against the live tree shapes) report through the
#: same counted-fallback funnel
count_fallback = _count_fallback


def _count_ok(event: str) -> None:
    try:
        _registry().counter(
            f"compile_aot_{event}_total",
            f"AOT artifact {event} operations that succeeded").inc()
    except Exception:
        pass


def aval_strs(exported) -> list:
    """Canonical short form ("float32[8,28]") — what the manifest stores
    and what ``_leaf_sig_strs`` derives from live call arguments."""
    return [a.str_short() for a in exported.in_avals]


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class AOTStore:
    """A directory of serialized executables + one atomic MANIFEST.json.

    Manifest schema (documented in docs/SERVING.md "AOT artifact
    contract")::

        {"schema_version": 1,
         "entries": {
           "<name>": {"uri": "<name>.jaxexport", "sha256": "...",
                      "size": 1234, "jax_version": "0.4.37",
                      "platforms": ["cpu"], "nr_devices": 1,
                      "in_avals": ["float32[8,28]", ...],
                      "calling_convention_version": 9,
                      "extra": {...caller metadata...}}}}

    The store usually lives beside the checkpoints it accelerates (a zoo
    entry's ``aot/`` sibling, or ``<checkpointDir>/aot/``).
    """

    def __init__(self, directory: str):
        self.directory = directory
        self.manifest_path = os.path.join(directory, MANIFEST_NAME)

    # ----------------------------------------------------------- manifest
    def manifest(self) -> Dict[str, Any]:
        try:
            with open(self.manifest_path) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and isinstance(doc.get("entries"), dict):
                return doc
        except FileNotFoundError:
            pass
        except Exception as e:
            log.warning("AOT manifest unreadable (%s); treating as empty", e)
        return {"schema_version": AOT_SCHEMA_VERSION, "entries": {}}

    def entries(self) -> Dict[str, Dict[str, Any]]:
        return self.manifest()["entries"]

    # ------------------------------------------------------------- export
    def save(self, name: str, exported, compiled=None,
             extra: Optional[Dict] = None) -> str:
        """Serialize one ``jax.export.Exported`` (+ optionally the
        matching pre-compiled ``jax.stages.Compiled``); artifacts then
        manifest (manifest-commits ordering, same as the checkpoint
        store)."""
        os.makedirs(self.directory, exist_ok=True)
        data = exported.serialize()
        uri = name + ARTIFACT_SUFFIX
        atomic_write_bytes(os.path.join(self.directory, uri), bytes(data))
        entry = {
            "uri": uri,
            "sha256": _sha256(bytes(data)),
            "size": len(data),
            "jax_version": jax.__version__,
            "platforms": list(exported.platforms),
            "nr_devices": int(exported.nr_devices),
            "in_avals": aval_strs(exported),
            "calling_convention_version":
                int(exported.calling_convention_version),
            "extra": dict(extra or {}),
        }
        if compiled is not None:
            from jax.experimental import serialize_executable as _se
            blob, in_tree, out_tree = _se.serialize(compiled)
            xdata = pickle.dumps({"xexec": blob, "in_tree": in_tree,
                                  "out_tree": out_tree})
            xuri = name + COMPILED_SUFFIX
            atomic_write_bytes(os.path.join(self.directory, xuri), xdata)
            entry["xexec_uri"] = xuri
            entry["xexec_sha256"] = _sha256(xdata)
            entry["xexec_size"] = len(xdata)
            entry["device_kind"] = jax.devices()[0].device_kind
        doc = self.manifest()
        doc["schema_version"] = AOT_SCHEMA_VERSION
        doc["entries"][name] = entry
        atomic_write_text(self.manifest_path,
                          json.dumps(doc, indent=1, sort_keys=True))
        if compiled is not None:
            # verify the fast layer round-trips ON THIS BACKEND before
            # publishing it: XLA cannot serialize every executable (e.g.
            # some CPU fusion thunks), and a worker should not pay a
            # doomed deserialize on every cold start — strip the layer
            # and let the portable jax.export artifact carry the entry
            if self.load_compiled(name) is None:
                log.warning("AOT compiled layer for %r failed its "
                            "publish-time round-trip; keeping only the "
                            "jax.export layer", name)
                for k in ("xexec_uri", "xexec_sha256", "xexec_size",
                          "device_kind"):
                    entry.pop(k, None)
                atomic_write_text(self.manifest_path,
                                  json.dumps(doc, indent=1, sort_keys=True))
        _count_ok("export")
        return uri

    # ------------------------------------------------- pre-compiled layer
    def load_compiled(self, name: str,
                      expect_nr_devices: Optional[int] = None,
                      expect_in_avals: Optional[Sequence[str]] = None):
        """Deserialize the pre-compiled executable layer, or None (counted
        fallback). Strictly pinned: jax version, platform, device kind and
        count, and input avals must all match the manifest entry."""
        doc = self.manifest()
        entry = doc["entries"].get(name)
        if entry is None:
            _count_fallback("missing", name)
            return None
        if "xexec_uri" not in entry:
            return None  # fast layer never published — not a fallback
        if doc.get("schema_version") != AOT_SCHEMA_VERSION:
            _count_fallback("schema_version", name)
            return None
        if entry.get("jax_version") != jax.__version__:
            _count_fallback("jax_version", name)
            return None
        if jax.default_backend() not in tuple(entry.get("platforms", ())):
            _count_fallback("platform", name)
            return None
        try:
            dev = jax.devices()[0]
        except Exception:
            _count_fallback("platform", name)
            return None
        if entry.get("device_kind") != dev.device_kind:
            _count_fallback("device_kind", name)
            return None
        if expect_nr_devices is not None and \
                int(entry.get("nr_devices", -1)) != int(expect_nr_devices):
            _count_fallback("mesh", name)
            return None
        if expect_in_avals is not None and \
                list(entry.get("in_avals", ())) != list(expect_in_avals):
            _count_fallback("avals", name)
            return None
        try:
            with open(os.path.join(self.directory,
                                   entry["xexec_uri"]), "rb") as f:
                xdata = f.read()
        except OSError:
            _count_fallback("missing", name)
            return None
        if _sha256(xdata) != entry.get("xexec_sha256"):
            _count_fallback("digest", name)
            return None
        try:
            from jax.experimental import serialize_executable as _se
            d = pickle.loads(xdata)
            compiled = _se.deserialize_and_load(d["xexec"], d["in_tree"],
                                                d["out_tree"])
        except Exception as e:
            log.warning("AOT compiled-executable load failed for %r: %s",
                        name, e)
            _count_fallback("deserialize", name)
            return None
        _count_ok("load_ok")
        return compiled

    # --------------------------------------------------------------- load
    def load(self, name: str, *, expect_platform: Optional[str] = None,
             expect_nr_devices: Optional[int] = None,
             expect_in_avals: Optional[Sequence[str]] = None):
        """Deserialize-or-fall-back: returns the ``Exported`` or None.

        Every None is a counted ``compile_aot_fallback_total{reason}`` —
        callers MUST treat None as "use cached_jit", never as an error.
        """
        doc = self.manifest()
        entry = doc["entries"].get(name)
        if entry is None:
            _count_fallback("missing", name)
            return None
        if doc.get("schema_version") != AOT_SCHEMA_VERSION:
            _count_fallback("schema_version", name)
            return None
        if entry.get("jax_version") != jax.__version__:
            # jax.export promises limited cross-version compat; stay strict
            # and recompile rather than risk a miscompiled serve
            _count_fallback("jax_version", name)
            return None
        path = os.path.join(self.directory, entry.get("uri", ""))
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            _count_fallback("missing", name)
            return None
        if _sha256(data) != entry.get("sha256"):
            _count_fallback("digest", name)  # truncated or corrupt artifact
            return None
        platform = expect_platform or jax.default_backend()
        if platform not in tuple(entry.get("platforms", ())):
            _count_fallback("platform", name)
            return None
        if expect_nr_devices is not None and \
                int(entry.get("nr_devices", -1)) != int(expect_nr_devices):
            _count_fallback("mesh", name)
            return None
        if expect_in_avals is not None and \
                list(entry.get("in_avals", ())) != list(expect_in_avals):
            _count_fallback("avals", name)
            return None
        try:
            from jax import export as jax_export
            exported = jax_export.deserialize(bytearray(data))
        except Exception as e:
            log.warning("AOT deserialize failed for %r: %s", name, e)
            _count_fallback("deserialize", name)
            return None
        # double-check the artifact itself agrees with its manifest row
        # (a hand-edited manifest must not smuggle a mismatched program in)
        if platform not in exported.platforms:
            _count_fallback("platform", name)
            return None
        if expect_nr_devices is not None and \
                int(exported.nr_devices) != int(expect_nr_devices):
            _count_fallback("mesh", name)
            return None
        if expect_in_avals is not None and \
                aval_strs(exported) != list(expect_in_avals):
            _count_fallback("avals", name)
            return None
        _count_ok("load_ok")
        return exported


def compile_for_export(jitfn, *specs):
    """Fresh AOT compile for serialization: bypasses the persistent cache
    (a cache-retrieved executable serializes without its symbol payload on
    XLA:CPU — see ``cache.uncached_compile``)."""
    from .cache import uncached_compile
    with uncached_compile():
        return jitfn.lower(*specs).compile()


def _leaf_sig_strs(args) -> list:
    """Aval strings for concrete call arguments, in the format
    ``aval_strs`` records at export time (flattened pytree order)."""
    out = []
    for leaf in jax.tree.leaves(args):
        shape = ",".join(str(d) for d in getattr(leaf, "shape", ()))
        dtype = jax.numpy.asarray(leaf).dtype.name \
            if not hasattr(leaf, "dtype") else leaf.dtype.name
        out.append(f"{dtype}[{shape}]")
    return out


def load_serving_callable(store: AOTStore, name: str, args,
                          expect_nr_devices: int = 1):
    """Resolve one manifest entry to the fastest usable callable.

    Order: pre-compiled executable (zero compile) -> ``jax.export``
    artifact wrapped once in ``cached_jit`` (zero tracing; compile rides
    the persistent cache) -> None (caller falls back to fresh JIT).
    ``args`` are the concrete call arguments; their avals gate both layers
    so a model that drifted since export can never run a stale program.
    """
    expect = _leaf_sig_strs(args)
    compiled = store.load_compiled(name, expect_nr_devices=expect_nr_devices,
                                   expect_in_avals=expect)
    if compiled is not None:
        return compiled
    exported = store.load(name, expect_nr_devices=expect_nr_devices,
                          expect_in_avals=expect)
    if exported is None:
        return None
    from .cache import cached_jit
    entry = store.entries().get(name, {})
    return cached_jit(exported.call,
                      key=("aot_exported", name, entry.get("sha256")),
                      name="aot_exported")
