"""Utility pipeline stages (reference: stages/ — SURVEY.md §2.3, 19 files)."""

from .basic import (Cacher, ClassBalancer, ClassBalancerModel, DropColumns,
                    EnsembleByKey, Explode, Lambda, MultiColumnAdapter,
                    RenameColumn, Repartition, SelectColumns,
                    StratifiedRepartition, Timer, TimerModel, UDFTransformer,
                    get_value_at, to_vector)
from .batching import (DynamicMiniBatchTransformer, FixedMiniBatchTransformer,
                       FlattenBatch, TimeIntervalMiniBatchTransformer)
from .text import (SummarizeData, TextPreprocessor, Trie, UnicodeNormalize)

__all__ = [
    "Cacher", "ClassBalancer", "ClassBalancerModel", "DropColumns",
    "DynamicMiniBatchTransformer", "EnsembleByKey", "Explode",
    "FixedMiniBatchTransformer", "FlattenBatch", "Lambda",
    "MultiColumnAdapter", "RenameColumn", "Repartition", "SelectColumns",
    "StratifiedRepartition", "SummarizeData", "TextPreprocessor",
    "TimeIntervalMiniBatchTransformer", "Timer", "TimerModel", "Trie",
    "UDFTransformer", "UnicodeNormalize", "get_value_at", "to_vector",
]
