"""Utility transformers — the reference's stages/ package (SURVEY.md §2.3 stages/).

Each class cites its reference analogue. These are host-side DataFrame ops: the
reference runs them as Spark plan nodes; here they are cheap columnar transforms, and
anything heavy (EnsembleByKey vector means, ClassBalancer counts) is vectorized numpy.
`Repartition`/`Cacher` exist for pipeline-surface parity — device sharding replaces
partitioning in the TPU design (see mmlspark_tpu.parallel.mesh), so they are metadata
hints rather than data movement.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model, PipelineStage, Transformer


class DropColumns(Transformer):
    """Reference: stages/DropColumns.scala:20."""
    cols = _p.Param("cols", "columns to drop", None)

    def __init__(self, cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if cols is not None:
            self.set("cols", list(cols))

    def transform(self, df: DataFrame) -> DataFrame:
        return df.drop(*(self.get("cols") or []))


class SelectColumns(Transformer):
    """Reference: stages/SelectColumns.scala:22."""
    cols = _p.Param("cols", "columns to keep", None)

    def __init__(self, cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if cols is not None:
            self.set("cols", list(cols))

    def transform(self, df: DataFrame) -> DataFrame:
        return df.select(*(self.get("cols") or []))


class RenameColumn(Transformer):
    """Reference: stages/RenameColumn.scala:19."""
    inputCol = _p.Param("inputCol", "column to rename", None)
    outputCol = _p.Param("outputCol", "new name", None)

    def transform(self, df: DataFrame) -> DataFrame:
        return df.with_column_renamed(self.get("inputCol"), self.get("outputCol"))


class Repartition(Transformer):
    """Reference: stages/Repartition.scala:19 — here a no-op passthrough: rows are
    sharded onto the device mesh at estimator boundaries, so host-side partition
    count has no meaning. Kept for pipeline-surface parity."""
    n = _p.Param("n", "requested partition count (ignored: device sharding "
                 "replaces partitioning)", 1, int)
    disable = _p.Param("disable", "passthrough switch", False, bool)

    def transform(self, df: DataFrame) -> DataFrame:
        return df


class Cacher(Transformer):
    """Reference: stages/Cacher.scala:13 — columns are already host-resident numpy;
    materialization is a no-op."""
    disable = _p.Param("disable", "passthrough switch", False, bool)

    def transform(self, df: DataFrame) -> DataFrame:
        return df


class Lambda(Transformer):
    """Arbitrary DataFrame=>DataFrame function as a serializable stage.

    Reference: stages/Lambda.scala:21 (Dataset=>Dataset function stage).
    """
    transformFunc = _p.Param("transformFunc", "df -> df function", None, complex=True)

    def __init__(self, transformFunc: Optional[Callable[[DataFrame], DataFrame]] = None,
                 **kw):
        super().__init__(**kw)
        if transformFunc is not None:
            self.set("transformFunc", transformFunc)

    def transform(self, df: DataFrame) -> DataFrame:
        fn = self.get("transformFunc")
        return fn(df) if fn is not None else df


class UDFTransformer(Transformer):
    """Apply a python function element-wise over an input column (or row-wise over
    several). Reference: stages/UDFTransformer.scala:25 + UDFParam/UDPyFParam.

    The UDF runs on host; vectorized functions may return a full column at once by
    setting ``vectorized=True`` (the TPU-friendly path — feed the whole column to a
    jitted function instead of the reference's per-row SQL UDF)."""
    inputCol = _p.Param("inputCol", "input column", None)
    inputCols = _p.Param("inputCols", "input columns (row-wise udf)", None)
    outputCol = _p.Param("outputCol", "output column", "output")
    udf = _p.Param("udf", "the function", None, complex=True)
    vectorized = _p.Param("vectorized", "whether udf takes whole columns", False, bool)

    def transform(self, df: DataFrame) -> DataFrame:
        fn = self.get("udf")
        if self.get("inputCols"):
            cols = [df[c] for c in self.get("inputCols")]
            if self.get("vectorized"):
                out = fn(*cols)
            else:
                out = [fn(*vals) for vals in zip(*cols)]
        else:
            col = df[self.get("inputCol")]
            out = fn(col) if self.get("vectorized") else [fn(v) for v in col]
        return df.with_column(self.get("outputCol"), np.asarray(out))


class Explode(Transformer):
    """Expand a ragged (object-dtype of sequences) column into one row per element.

    Reference: stages/Explode.scala:16."""
    inputCol = _p.Param("inputCol", "ragged column to explode", None)
    outputCol = _p.Param("outputCol", "exploded output column", None)

    def transform(self, df: DataFrame) -> DataFrame:
        name = self.get("inputCol")
        out_name = self.get("outputCol") or name
        col = df[name]
        lengths = np.fromiter((len(v) for v in col), dtype=np.int64, count=len(col))
        idx = np.repeat(np.arange(len(col)), lengths)
        flat: List[Any] = []
        for v in col:
            flat.extend(v)
        rep = df.take(idx)
        return rep.with_column(out_name, np.asarray(flat, dtype=object)
                               if any(isinstance(x, str) for x in flat[:8])
                               else np.asarray(flat))


class EnsembleByKey(Transformer):
    """Group rows by key column(s) and average scalar/vector columns.

    Reference: stages/EnsembleByKey.scala:22 (incl. VectorAvg UDAF :155).
    Vectorized: sort-by-key + np.add.reduceat replaces the reference's UDAF.
    """
    keys = _p.Param("keys", "key columns", None)
    cols = _p.Param("cols", "value columns to average", None)
    colNames = _p.Param("colNames", "output names for averaged columns", None)
    strategy = _p.Param("strategy", "aggregation strategy", "mean")
    collapseGroup = _p.Param("collapseGroup", "emit one row per group", True, bool)

    def transform(self, df: DataFrame) -> DataFrame:
        keys = self.get("keys")
        cols = self.get("cols")
        names = self.get("colNames") or [f"{c}_mean" for c in cols]
        key_arrays = [df[k] for k in keys]
        # stable factorization of composite keys
        seen: Dict[Any, int] = {}
        gids = np.empty(len(df), dtype=np.int64)
        for i, tup in enumerate(zip(*key_arrays)):
            t = tuple(x.item() if hasattr(x, "item") else x for x in tup)
            gids[i] = seen.setdefault(t, len(seen))
        order = np.argsort(gids, kind="stable")
        sorted_gids = gids[order]
        boundaries = np.flatnonzero(np.diff(sorted_gids, prepend=-1))
        counts = np.diff(np.append(boundaries, len(df)))
        out_cols: Dict[str, np.ndarray] = {}
        first_idx = order[boundaries]
        for k in keys:
            out_cols[k] = df[k][first_idx]
        for c, n in zip(cols, names):
            v = df[c]
            vs = np.asarray(v, dtype=np.float64)[order]
            sums = np.add.reduceat(vs, boundaries, axis=0)
            means = sums / (counts[:, None] if vs.ndim > 1 else counts)
            out_cols[n] = means
        if self.get("collapseGroup"):
            return DataFrame(out_cols)
        # broadcast group means back onto every row
        out = df
        inv = np.empty(len(df), dtype=np.int64)
        inv[order] = np.repeat(np.arange(len(boundaries)), counts)
        for c, n in zip(cols, names):
            out = out.with_column(n, out_cols[n][inv])
        return out


class ClassBalancer(Estimator):
    """Weight column = max(count)/count(label) — inverse-frequency balancing.

    Reference: stages/ClassBalancer.scala:27."""
    inputCol = _p.Param("inputCol", "label column", "label")
    outputCol = _p.Param("outputCol", "weight column", "weight")
    broadcastJoin = _p.Param("broadcastJoin", "unused (host join)", True, bool)

    def _fit(self, df: DataFrame) -> "ClassBalancerModel":
        col = df[self.get("inputCol")]
        values, counts = np.unique(col, return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        model = ClassBalancerModel(
            values=[v.item() if hasattr(v, "item") else v for v in values],
            weights=weights)
        model.set("inputCol", self.get("inputCol"))
        model.set("outputCol", self.get("outputCol"))
        return model


class ClassBalancerModel(Model):
    inputCol = _p.Param("inputCol", "label column", "label")
    outputCol = _p.Param("outputCol", "weight column", "weight")
    values = _p.Param("values", "distinct label values", None, complex=True)
    weights = _p.Param("weights", "weight per value", None, complex=True)

    def __init__(self, values=None, weights=None, **kw):
        super().__init__(**kw)
        if values is not None:
            self.set("values", list(values))
        if weights is not None:
            self.set("weights", np.asarray(weights, np.float64))

    def transform(self, df: DataFrame) -> DataFrame:
        lookup = {v: w for v, w in zip(self.get("values"), self.get("weights"))}
        col = df[self.get("inputCol")]
        w = np.fromiter((lookup[v.item() if hasattr(v, "item") else v] for v in col),
                        dtype=np.float64, count=len(col))
        return df.with_column(self.get("outputCol"), w)


class StratifiedRepartition(Transformer):
    """Rebalance rows so every label value appears spread across the dataset —
    the reference uses per-label sampleByKeyExact + RangePartitioner so each LightGBM
    partition sees all labels (stages/StratifiedRepartition.scala:29). On TPU the
    analogous invariant is that each *device shard* sees all labels; we interleave
    rows round-robin by label so any contiguous shard split is label-complete."""
    labelCol = _p.Param("labelCol", "label column", "label")
    mode = _p.Param("mode", "equal | original | mixed", "mixed")
    seed = _p.Param("seed", "shuffle seed", 0, int)

    def transform(self, df: DataFrame) -> DataFrame:
        labels = df[self.get("labelCol")]
        rng = np.random.default_rng(self.get("seed"))
        values = np.unique(labels)
        per_label = []
        for v in values:
            idx = np.flatnonzero(labels == v)
            rng.shuffle(idx)
            per_label.append(idx)
        # round-robin interleave (ragged: shorter lists simply run out)
        longest = max(len(ix) for ix in per_label)
        order = []
        for i in range(longest):
            for ix in per_label:
                if i < len(ix):
                    order.append(ix[i])
        return df.take(np.asarray(order))


class MultiColumnAdapter(Transformer):
    """Map a single-column stage over N (input, output) column pairs.

    Reference: stages/MultiColumnAdapter.scala:18."""
    baseStage = _p.Param("baseStage", "1-col stage to replicate", None, complex=True)
    inputCols = _p.Param("inputCols", "input columns", None)
    outputCols = _p.Param("outputCols", "output columns", None)

    def transform(self, df: DataFrame) -> DataFrame:
        base: PipelineStage = self.get("baseStage")
        cur = df
        for i, o in zip(self.get("inputCols"), self.get("outputCols")):
            stage = base.copy({"inputCol": i, "outputCol": o})
            if isinstance(stage, Estimator):
                cur = stage.fit(cur).transform(cur)
            else:
                cur = stage.transform(cur)
        return cur


class Timer(Estimator):
    """Wrap a stage; log wall-time of fit/transform.

    Reference: stages/Timer.scala:18+. Times include device sync (block_until_ready
    happens inside estimators), so numbers are honest end-to-end latencies."""
    stage = _p.Param("stage", "wrapped stage", None, complex=True)
    logToScala = _p.Param("logToScala", "print timing (surface parity name)", True, bool)
    disableMaterialization = _p.Param("disableMaterialization", "unused", True, bool)

    def _fit(self, df: DataFrame) -> "TimerModel":
        stage = self.get("stage")
        t0 = time.perf_counter()
        if isinstance(stage, Estimator):
            fitted = stage.fit(df)
        else:
            fitted = stage
        elapsed = time.perf_counter() - t0
        if self.get("logToScala"):
            print(f"[Timer] fit {type(stage).__name__}: {elapsed:.4f}s")
        model = TimerModel(stage=fitted)
        model.set("logToScala", self.get("logToScala"))
        return model


class TimerModel(Model):
    stage = _p.Param("stage", "wrapped fitted stage", None, complex=True)
    logToScala = _p.Param("logToScala", "print timing", True, bool)

    def __init__(self, stage=None, **kw):
        super().__init__(**kw)
        if stage is not None:
            self.set("stage", stage)

    def transform(self, df: DataFrame) -> DataFrame:
        t0 = time.perf_counter()
        out = self.get("stage").transform(df)
        elapsed = time.perf_counter() - t0
        if self.get("logToScala"):
            print(f"[Timer] transform {type(self.get('stage')).__name__}: "
                  f"{elapsed:.4f}s")
        return out


# -------------------------------------------------------------------- udfs
# Reference: stages/udfs.scala (`get_value_at`, `to_vector`)

def get_value_at(col: np.ndarray, index: int) -> np.ndarray:
    """Extract element `index` from a vector column."""
    return np.asarray(col)[:, index]


def to_vector(col: np.ndarray) -> np.ndarray:
    """Coerce an array/list column to a dense 2-D vector column."""
    return np.stack([np.asarray(v, dtype=np.float64) for v in col])
