"""Text utility transformers.

Reference: stages/TextPreprocessor.scala:96 (trie-based normalization, Trie :15),
stages/UnicodeNormalize.scala, stages/SummarizeData.scala:100.
"""

from __future__ import annotations

import unicodedata
from typing import Dict, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Transformer


class Trie:
    """Left-to-right longest-match trie. Reference: stages/TextPreprocessor.scala:15."""

    __slots__ = ("children", "value")

    def __init__(self):
        self.children: Dict[str, "Trie"] = {}
        self.value: Optional[str] = None

    def put(self, key: str, value: str) -> None:
        node = self
        for ch in key:
            node = node.children.setdefault(ch, Trie())
        node.value = value

    def map_text(self, text: str) -> str:
        out = []
        i, n = 0, len(text)
        while i < n:
            node, j, best_end, best_val = self, i, -1, None
            while j < n and text[j] in node.children:
                node = node.children[text[j]]
                j += 1
                if node.value is not None:
                    best_end, best_val = j, node.value
            if best_val is not None:
                out.append(best_val)
                i = best_end
            else:
                out.append(text[i])
                i += 1
        return "".join(out)


class TextPreprocessor(Transformer):
    """Apply a substitution map via longest-match trie scan.

    Reference: stages/TextPreprocessor.scala:96."""
    inputCol = _p.Param("inputCol", "input text column", "input")
    outputCol = _p.Param("outputCol", "output text column", "output")
    map = _p.Param("map", "substring -> replacement map", None, complex=True)
    normFunc = _p.Param("normFunc", "pre-normalization: lowerCase|identity", "identity")

    def transform(self, df: DataFrame) -> DataFrame:
        trie = Trie()
        for k, v in (self.get("map") or {}).items():
            trie.put(k, v)
        norm = str.lower if self.get("normFunc") == "lowerCase" else (lambda s: s)
        col = df[self.get("inputCol")]
        out = np.empty(len(col), dtype=object)
        for i, text in enumerate(col):
            out[i] = trie.map_text(norm(str(text)))
        return df.with_column(self.get("outputCol"), out)


class UnicodeNormalize(Transformer):
    """Unicode normalization (NFC/NFD/NFKC/NFKD) + optional lowercasing.

    Reference: stages/UnicodeNormalize.scala."""
    inputCol = _p.Param("inputCol", "input text column", "input")
    outputCol = _p.Param("outputCol", "output text column", "output")
    form = _p.Param("form", "NFC | NFD | NFKC | NFKD", "NFKD")
    lower = _p.Param("lower", "lowercase after normalizing", True, bool)

    def transform(self, df: DataFrame) -> DataFrame:
        form = self.get("form").upper()
        lower = self.get("lower")
        col = df[self.get("inputCol")]
        out = np.empty(len(col), dtype=object)
        for i, text in enumerate(col):
            s = unicodedata.normalize(form, str(text))
            out[i] = s.lower() if lower else s
        return df.with_column(self.get("outputCol"), out)


class SummarizeData(Transformer):
    """Per-column summary statistics DataFrame.

    Reference: stages/SummarizeData.scala:100 — counts / quantiles / sample stats /
    percentiles per column, toggled by flags."""
    counts = _p.Param("counts", "emit count/unique/missing", True, bool)
    basic = _p.Param("basic", "emit min/max/mean/stddev", True, bool)
    sample = _p.Param("sample", "emit variance/skew/kurtosis", True, bool)
    percentiles = _p.Param("percentiles", "emit p0.5/1/5/25/50/75/95/99/99.5", True, bool)
    errorThreshold = _p.Param("errorThreshold", "quantile error (exact here)", 0.0, float)

    _PCTS = [0.5, 1, 5, 25, 50, 75, 95, 99, 99.5]

    def transform(self, df: DataFrame) -> DataFrame:
        rows: Dict[str, list] = {"Feature": []}
        want = []
        if self.get("counts"):
            want += ["Count", "Unique Value Count", "Missing Value Count"]
        if self.get("basic"):
            want += ["Min", "Max", "Mean", "Standard Deviation"]
        if self.get("sample"):
            want += ["Sample Variance", "Sample Skewness", "Sample Kurtosis"]
        if self.get("percentiles"):
            want += [f"P{p}" for p in self._PCTS]
        for k in want:
            rows[k] = []
        for name in df.columns:
            col = df[name]
            if col.ndim > 1 or col.dtype.kind not in "biuf":
                continue
            v = np.asarray(col, np.float64)
            finite = v[np.isfinite(v)]
            rows["Feature"].append(name)
            if self.get("counts"):
                rows["Count"].append(float(len(v)))
                rows["Unique Value Count"].append(float(len(np.unique(finite))))
                rows["Missing Value Count"].append(float(len(v) - len(finite)))
            if self.get("basic"):
                rows["Min"].append(float(finite.min()) if len(finite) else np.nan)
                rows["Max"].append(float(finite.max()) if len(finite) else np.nan)
                rows["Mean"].append(float(finite.mean()) if len(finite) else np.nan)
                rows["Standard Deviation"].append(
                    float(finite.std(ddof=1)) if len(finite) > 1 else np.nan)
            if self.get("sample"):
                if len(finite) > 2:
                    m = finite.mean()
                    d = finite - m
                    var = d.var(ddof=1) * len(finite) / max(len(finite) - 1, 1)
                    s2 = d.std(ddof=1)
                    skew = (np.mean(d ** 3) / s2 ** 3) if s2 > 0 else np.nan
                    kurt = (np.mean(d ** 4) / s2 ** 4 - 3.0) if s2 > 0 else np.nan
                else:
                    var = skew = kurt = np.nan
                rows["Sample Variance"].append(float(finite.var(ddof=1))
                                               if len(finite) > 1 else np.nan)
                rows["Sample Skewness"].append(float(skew))
                rows["Sample Kurtosis"].append(float(kurt))
            if self.get("percentiles"):
                for p in self._PCTS:
                    rows[f"P{p}"].append(
                        float(np.percentile(finite, p)) if len(finite) else np.nan)
        data = {"Feature": np.array(rows["Feature"], dtype=object)}
        for k in want:
            data[k] = np.asarray(rows[k], np.float64)
        return DataFrame(data)
