"""Mini-batching transformers.

Reference: stages/MiniBatchTransformer.scala (DynamicMiniBatchTransformer:43,
TimeIntervalMiniBatchTransformer:66, FixedMiniBatchTransformer:139, FlattenBatch:174)
+ iterator machinery stages/Batchers.scala:12-140.

In the reference these exist to amortize per-row JNI/HTTP overhead. On TPU, batching is
what makes the MXU useful at all: a batched column is one jit call. The transformers
turn an N-row DataFrame into ceil(N/b) rows whose cells are arrays (object columns of
per-batch arrays), and FlattenBatch undoes it. Estimators that are batch-aware
(DeepModel) consume these directly.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Transformer


def _batch_column(col: np.ndarray, bounds) -> np.ndarray:
    out = np.empty(len(bounds) - 1, dtype=object)
    for i in range(len(bounds) - 1):
        out[i] = col[bounds[i]:bounds[i + 1]]
    return out


class FixedMiniBatchTransformer(Transformer):
    """Group rows into fixed-size batches. Reference: MiniBatchTransformer.scala:139.

    `buffered` exists for surface parity (the reference prefetches with a buffer
    thread); host columns are already materialized here."""
    batchSize = _p.Param("batchSize", "rows per batch", 10, int)
    buffered = _p.Param("buffered", "prefetch batches (no-op)", False, bool)
    maxBufferSize = _p.Param("maxBufferSize", "prefetch buffer cap (no-op)", 2147483647, int)

    def transform(self, df: DataFrame) -> DataFrame:
        b = int(self.get("batchSize"))
        n = len(df)
        bounds = list(range(0, n, b)) + [n]
        out = DataFrame()
        for name in df.columns:
            out._cols[name] = _batch_column(df[name], bounds)
        return out


class DynamicMiniBatchTransformer(Transformer):
    """Reference: MiniBatchTransformer.scala:43 — batches whatever has arrived, up to
    maxBatchSize. Without a streaming source the whole input is 'available', so this
    emits one batch capped at maxBatchSize each."""
    maxBatchSize = _p.Param("maxBatchSize", "max rows per batch", 2147483647, int)

    def transform(self, df: DataFrame) -> DataFrame:
        return FixedMiniBatchTransformer(
            batchSize=min(int(self.get("maxBatchSize")), max(len(df), 1))
        ).transform(df)


class TimeIntervalMiniBatchTransformer(Transformer):
    """Reference: MiniBatchTransformer.scala:66 — batch rows arriving within a time
    interval. Batch-mode equivalent: same as Dynamic (all rows are 'within interval');
    the serving path (mmlspark_tpu.io.serving) does real time-windowed batching."""
    millisToWait = _p.Param("millisToWait", "interval in ms", 1000, int)
    maxBatchSize = _p.Param("maxBatchSize", "max rows per batch", 2147483647, int)

    def transform(self, df: DataFrame) -> DataFrame:
        return DynamicMiniBatchTransformer(
            maxBatchSize=self.get("maxBatchSize")).transform(df)


class FlattenBatch(Transformer):
    """Unbatch: explode every object-array cell back into rows.

    Reference: MiniBatchTransformer.scala:174."""

    def transform(self, df: DataFrame) -> DataFrame:
        if not df.columns:
            return df
        first = df[df.columns[0]]
        lengths = np.fromiter((len(v) for v in first), dtype=np.int64,
                              count=len(first))
        out = DataFrame()
        for name in df.columns:
            col = df[name]
            parts = [np.asarray(v) for v in col]
            if parts:
                try:
                    out._cols[name] = np.concatenate(parts, axis=0)
                except ValueError:  # ragged cells -> object column
                    flat = np.empty(int(lengths.sum()), dtype=object)
                    i = 0
                    for v in col:
                        for x in v:
                            flat[i] = x
                            i += 1
                    out._cols[name] = flat
            else:
                out._cols[name] = np.empty(0)
        return out
