"""Golden-metric benchmark machinery — accuracy-regression gates.

Reference: core/test/benchmarks/Benchmarks.scala:16-90+ — metric values
recorded to CSV under src/test/resources/benchmarks/ and compared with
per-entry precision: accuracy-regression tests, not wall-clock. Same protocol
here: `Benchmarks(csv_path)` accumulates (name, value, precision) entries;
`verify()` compares against the committed CSV, or writes it when absent
(record mode, like the reference's regenerate flow).
"""

from __future__ import annotations

import csv
import os
from typing import List, Tuple


class Benchmarks:
    def __init__(self, csv_path: str):
        self.csv_path = csv_path
        self.entries: List[Tuple[str, float, float]] = []

    def add(self, name: str, value: float, precision: float) -> None:
        self.entries.append((name, float(value), float(precision)))

    compare_value = add  # reference surface name (compareValue)

    def _read_golden(self):
        golden = {}
        with open(self.csv_path) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                golden[row[0]] = (float(row[1]), float(row[2]))
        return golden

    def _write_golden(self) -> None:
        os.makedirs(os.path.dirname(self.csv_path), exist_ok=True)
        with open(self.csv_path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["# name", "value", "precision"])
            for name, value, precision in self.entries:
                w.writerow([name, f"{value:.6f}", precision])

    def verify(self) -> None:
        """Compare recorded entries against the golden CSV; write the CSV if
        it does not exist yet (record mode)."""
        if not os.path.exists(self.csv_path):
            self._write_golden()
            return
        golden = self._read_golden()
        # MMLSPARK_BENCH_RECORD=1: append rows for genuinely-new gate names
        # (several tests share one CSV, so whole-file record mode cannot
        # cover a name added to just one of them). Off by default — an
        # unknown name then FAILS, so a renamed/typo'd gate can't silently
        # re-record itself alongside a regression.
        record_new = os.environ.get("MMLSPARK_BENCH_RECORD",
                                    "").lower() in ("1", "true")
        errors = []
        new_rows = []
        for name, value, precision in self.entries:
            if name not in golden:
                if record_new:
                    new_rows.append((name, value, precision))
                else:
                    errors.append(f"{name}: no golden entry (run with "
                                  f"MMLSPARK_BENCH_RECORD=1 to record)")
                continue
            expected, tol = golden[name]
            if abs(value - expected) > tol:
                errors.append(f"{name}: got {value:.6f}, "
                              f"expected {expected:.6f} ± {tol}")
        if errors:
            raise AssertionError("benchmark regressions:\n" +
                                 "\n".join(errors))
        if new_rows:
            with open(self.csv_path, "a", newline="") as f:
                w = csv.writer(f)
                for name, value, precision in new_rows:
                    w.writerow([name, f"{value:.6f}", precision])
