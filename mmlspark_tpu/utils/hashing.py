"""Murmur3 x86 32-bit hashing — the feature-hashing primitive.

Reference: the reference hashes features in two places — Spark's HashingTF (murmur3)
used by featurize/text/TextFeaturizer.scala and the VW murmur re-implemented on the
JVM in vw/VowpalWabbitMurmurWithPrefix.scala:77 (prefix-state optimization). This
module is the single host-side implementation; mmlspark_tpu.utils.native swaps in the
C++ batch kernel when the native runtime library is available.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def _mix_k(k1: int) -> int:
    k1 = (k1 * _C1) & _M32
    k1 = _rotl32(k1, 15)
    return (k1 * _C2) & _M32


def _mix_blocks(h1: int, data: bytes) -> int:
    """Mix all whole 4-byte blocks of data into state h1."""
    for i in range(len(data) // 4):
        k1 = int.from_bytes(data[i * 4:(i + 1) * 4], "little")
        h1 ^= _mix_k(k1)
        h1 = _rotl32(h1, 13)
        h1 = (h1 * 5 + 0xE6546B64) & _M32
    return h1


def _tail_and_finalize(h1: int, tail: bytes, total_len: int) -> int:
    """Mix the <4-byte tail and apply murmur3 finalization for total_len bytes."""
    k1 = 0
    if len(tail) >= 3:
        k1 ^= tail[2] << 16
    if len(tail) >= 2:
        k1 ^= tail[1] << 8
    if len(tail) >= 1:
        k1 ^= tail[0]
        h1 ^= _mix_k(k1)
    h1 ^= total_len
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M32
    h1 ^= h1 >> 16
    return h1


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """Murmur3 x86_32 over bytes. Matches Spark/Scala murmur3 on the same bytes."""
    h1 = _mix_blocks(seed & _M32, data)
    return _tail_and_finalize(h1, data[(len(data) // 4) * 4:], len(data))


class MurmurWithPrefix:
    """Hash strings under a constant prefix without re-hashing the prefix.

    Reference: vw/VowpalWabbitMurmurWithPrefix.scala:77 — precomputes the murmur
    state for whole 4-byte blocks of the prefix, then finishes with each suffix.
    Exact same output as murmur3_32(prefix + s)."""

    def __init__(self, prefix: str, seed: int = 0):
        self.prefix = prefix.encode("utf-8")
        self.seed = seed
        nblocks = len(self.prefix) // 4
        self._state = _mix_blocks(seed & _M32, self.prefix[:nblocks * 4])
        self._rem = self.prefix[nblocks * 4:]

    def hash(self, s: str) -> int:
        data = self._rem + s.encode("utf-8")
        h1 = _mix_blocks(self._state, data)
        total = len(self.prefix) + len(s.encode("utf-8"))
        return _tail_and_finalize(h1, data[(len(data) // 4) * 4:], total)


def hash_strings(strings: Iterable[str], num_bits: int, seed: int = 0,
                 ) -> np.ndarray:
    """Batch-hash strings into [0, 2**num_bits) buckets.

    Uses the native C++ kernel when available (utils/native.py), else pure python."""
    from . import native
    mask = (1 << num_bits) - 1
    lib = native.get_lib()
    if lib is not None:
        return native.hash_strings(strings, mask, seed)
    return np.fromiter(
        (murmur3_32(s.encode("utf-8"), seed) & mask for s in strings),
        dtype=np.int64)


def hashing_tf(docs: Sequence[Sequence[str]], num_features: int, seed: int = 0,
               binary: bool = False, sparse: bool = False):
    """Term-frequency matrix by hashed bucket — Spark HashingTF equivalent
    (used by TextFeaturizer.scala's hashingTF stage). Dense by default (the
    TPU kernels want dense matrices at modest widths); sparse=True returns
    scipy CSR for wide spaces (2^18), which the DataFrame keeps sparse and
    `featurize.SparseFeatureBundler` packs dense."""
    from . import native
    n = len(docs)
    pow2 = (num_features & (num_features - 1)) == 0
    flat = [str(t) for doc in docs for t in doc]
    lengths = [len(doc) for doc in docs]
    rows = np.repeat(np.arange(n), lengths)
    if flat:
        if pow2 and native.get_lib() is not None:
            # native batch path: hash all terms of all docs in one C++ call
            buckets = native.hash_strings(flat, num_features - 1, seed)
        else:
            mask = num_features - 1 if pow2 else None
            buckets = np.fromiter(
                ((murmur3_32(t.encode("utf-8"), seed) & mask)
                 if mask is not None
                 else (murmur3_32(t.encode("utf-8"), seed) % num_features)
                 for t in flat), dtype=np.int64, count=len(flat))
    else:
        buckets = np.zeros(0, np.int64)
    if sparse:
        import scipy.sparse as sp
        out = sp.csr_matrix(
            (np.ones(len(flat), np.float32), (rows, buckets)),
            shape=(n, num_features))
        out.sum_duplicates()
        if binary:
            out.data = np.minimum(out.data, 1.0)
        return out
    out = np.zeros((n, num_features), np.float32)
    if len(flat):
        if binary:
            out[rows, buckets] = 1.0
        else:
            np.add.at(out, (rows, buckets), 1.0)
    return out
