// Native host runtime kernels for mmlspark_tpu.
//
// Reference analogue: the reference embeds C++ engines via JNI (LightGBM, VW, OpenCV;
// loaded by core/env/NativeLoader.java:28-100). The TPU build keeps compute on the
// accelerator; the C++ here covers host-side hot paths the reference also did natively:
//   - murmur3 batch feature hashing (vw/VowpalWabbitMurmurWithPrefix.scala:77 role)
//   - quantile-bin assignment of dense matrices (LGBM_DatasetCreateFromMat role:
//     reference lightgbm/LightGBMDataset.scala:12-101 marshals rows into native bins)
//   - image resize/normalize (opencv/ImageTransformer.scala role)
// Exposed with a plain C ABI and loaded from Python via ctypes (no pybind11).

#include <cstdint>
#include <cstdlib>
#include <limits>
#include <cstring>
#include <cmath>
#include <algorithm>

extern "C" {

// ---------------------------------------------------------------- murmur3
static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16; h *= 0x85ebca6b;
  h ^= h >> 13; h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

uint32_t mml_murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const int64_t nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;
  const uint32_t* blocks = (const uint32_t*)(data);
  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k1;
    std::memcpy(&k1, blocks + i, 4);
    k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2;
    h1 ^= k1; h1 = rotl32(h1, 13); h1 = h1 * 5 + 0xe6546b64;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= tail[1] << 8;  [[fallthrough]];
    case 1: k1 ^= tail[0];
            k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h1 ^= k1;
  }
  h1 ^= (uint32_t)len;
  return fmix32(h1);
}

// Batch-hash n strings (concatenated utf-8 bytes + offsets) into out[i] = h & mask.
void mml_hash_strings(const uint8_t* bytes, const int64_t* offsets, int64_t n,
                      uint32_t seed, uint32_t mask, int64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* s = bytes + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    out[i] = (int64_t)(mml_murmur3_32(s, len, seed) & mask);
  }
}

// ------------------------------------------------------- quantile binning
// Assign each value to its quantile bin. data is row-major [n, f]; edges is
// [f, num_edges] sorted ascending (padded with +inf); out is [n, f] int32.
// Row-major iteration (the original column-major walk strided f*4 bytes per
// step and was cache-hostile on the 1-vCPU host). Since edges are sorted,
// searchsorted-left == count of (v > e[k]); the branchless vectorized count
// beats branchy binary search up to 256 edges (measured 3.4x at 255); only
// edge tables too large for L2 fall back to scalar paths below.
void mml_bin_matrix(const float* data, int64_t n, int64_t f,
                    const double* edges, int64_t num_edges, int32_t* out) {
  // Fast path: transposed float threshold table, vertical SIMD across the
  // feature axis. For each double edge e pick the smallest float t with
  // (double)t > e; then for float v (exact as double), v > e  <=>  v >= t,
  // so the float compare reproduces the double searchsorted-left bin
  // EXACTLY at twice the SIMD width and half the table bytes. +inf padding
  // edges map to t = NaN (v >= NaN is always false), and a NaN value fails
  // every compare, landing in bin 0 — the missing-bin convention — with no
  // branch at all. Table layout is [num_edges, f] so the inner loop is a
  // contiguous compare-accumulate over the row; gated to tables that fit
  // comfortably in L2 since every row re-reads the table.
  constexpr int64_t W = 32;           // feature chunk = 2 AVX-512 vectors
  const int64_t fp = (f + W - 1) / W * W;   // padded table stride
  if (num_edges <= 256 && num_edges * fp * (int64_t)sizeof(float) <= 1 << 20) {
    float* T = (float*)malloc((size_t)(num_edges * fp) * sizeof(float));
    if (T != nullptr) {
      const float nanv = std::numeric_limits<float>::quiet_NaN();
      int64_t k_used = 0;  // skip trailing all-padding edge rows
      for (int64_t k = 0; k < num_edges; k++)
        for (int64_t j = 0; j < fp; j++) T[k * fp + j] = nanv;
      for (int64_t j = 0; j < f; j++) {
        for (int64_t k = 0; k < num_edges; k++) {
          double e = edges[j * num_edges + k];
          if (e == std::numeric_limits<double>::infinity()) continue;
          float t = (float)e;  // round-to-nearest
          if (!((double)t > e))
            t = std::nextafter(t, std::numeric_limits<float>::infinity());
          if (k + 1 > k_used) k_used = k + 1;
          T[k * fp + j] = t;
        }
      }
      // k innermost over fixed-width chunks: row values and counts live in
      // vector registers across the whole edge sweep (one table load +
      // compare + subtract per 32 features per edge); two rows in flight
      // amortize each table load. Pad lanes hold NaN values against NaN
      // thresholds, so they count 0 and never touch `out`.
      auto chunk1 = [&](int64_t i, int64_t j0) {
        int32_t acc[W];
        float rv[W];
        for (int64_t w = 0; w < W; w++) {
          const int64_t j = j0 + w;
          acc[w] = 0;
          rv[w] = j < f ? data[i * f + j] : nanv;
        }
        for (int64_t k = 0; k < k_used; k++) {
          const float* __restrict__ t = T + k * fp + j0;
          for (int64_t w = 0; w < W; w++) acc[w] += (rv[w] >= t[w]);
        }
        for (int64_t w = 0; w < W && j0 + w < f; w++)
          out[i * f + j0 + w] = acc[w];
      };
      int64_t i = 0;
      for (; i + 2 <= n; i += 2) {
        for (int64_t j0 = 0; j0 < fp; j0 += W) {
          int32_t acc[2][W];
          float rv[2][W];
          for (int r = 0; r < 2; r++)
            for (int64_t w = 0; w < W; w++) {
              const int64_t j = j0 + w;
              acc[r][w] = 0;
              rv[r][w] = j < f ? data[(i + r) * f + j] : nanv;
            }
          for (int64_t k = 0; k < k_used; k++) {
            const float* __restrict__ t = T + k * fp + j0;
            for (int r = 0; r < 2; r++)
              for (int64_t w = 0; w < W; w++) acc[r][w] += (rv[r][w] >= t[w]);
          }
          for (int r = 0; r < 2; r++)
            for (int64_t w = 0; w < W && j0 + w < f; w++)
              out[(i + r) * f + j0 + w] = acc[r][w];
        }
      }
      for (; i < n; i++)
        for (int64_t j0 = 0; j0 < fp; j0 += W) chunk1(i, j0);
      free(T);
      return;
    }
  }
  if (num_edges <= 128) {
    for (int64_t i = 0; i < n; i++) {
      const float* row = data + i * f;
      int32_t* orow = out + i * f;
      for (int64_t j = 0; j < f; j++) {
        float v = row[j];
        // NaN -> bin 0 (missing bin), matching host-side binning convention
        if (std::isnan(v)) { orow[j] = 0; continue; }
        const double* e = edges + j * num_edges;
        double vd = (double)v;
        int32_t c = 0;
        for (int64_t k = 0; k < num_edges; k++) c += (vd > e[k]);
        orow[j] = c;
      }
    }
    return;
  }
  for (int64_t i = 0; i < n; i++) {
    const float* row = data + i * f;
    int32_t* orow = out + i * f;
    for (int64_t j = 0; j < f; j++) {
      float v = row[j];
      if (std::isnan(v)) { orow[j] = 0; continue; }
      const double* e = edges + j * num_edges;
      int32_t lo = 0, hi = (int32_t)num_edges;
      while (lo < hi) {
        int32_t mid = (lo + hi) / 2;
        if ((double)v > e[mid]) lo = mid + 1; else hi = mid;
      }
      orow[j] = lo;
    }
  }
}

// ------------------------------------------------------- image kernels
// Bilinear resize HWC uint8 -> HWC uint8.
void mml_resize_bilinear_u8(const uint8_t* src, int64_t sh, int64_t sw, int64_t c,
                            uint8_t* dst, int64_t dh, int64_t dw) {
  const double ry = dh > 1 ? (double)(sh - 1) / (dh - 1) : 0.0;
  const double rx = dw > 1 ? (double)(sw - 1) / (dw - 1) : 0.0;
  for (int64_t y = 0; y < dh; y++) {
    double fy = y * ry;
    int64_t y0 = (int64_t)fy;
    int64_t y1 = std::min(y0 + 1, sh - 1);
    double wy = fy - y0;
    for (int64_t x = 0; x < dw; x++) {
      double fx = x * rx;
      int64_t x0 = (int64_t)fx;
      int64_t x1 = std::min(x0 + 1, sw - 1);
      double wx = fx - x0;
      for (int64_t k = 0; k < c; k++) {
        double v00 = src[(y0 * sw + x0) * c + k];
        double v01 = src[(y0 * sw + x1) * c + k];
        double v10 = src[(y1 * sw + x0) * c + k];
        double v11 = src[(y1 * sw + x1) * c + k];
        double v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                   v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(y * dw + x) * c + k] = (uint8_t)std::lround(std::min(255.0, std::max(0.0, v)));
      }
    }
  }
}

// HWC uint8 -> CHW float32 unroll with per-channel scale/shift (normalization).
void mml_unroll_chw(const uint8_t* src, int64_t h, int64_t w, int64_t c,
                    const float* scale, const float* shift, float* dst) {
  for (int64_t k = 0; k < c; k++)
    for (int64_t y = 0; y < h; y++)
      for (int64_t x = 0; x < w; x++)
        dst[k * h * w + y * w + x] = src[(y * w + x) * c + k] * scale[k] + shift[k];
}

// ---------------------------------------------------------- csv parsing
// Numeric-CSV fast path (the host data-loader role Spark's csv reader
// plays for the reference; BinaryFileFormat.scala is the binary analogue).
// Parses `n_rows * n_cols` numbers from a comma/`sep`-separated text
// buffer into `out` (row-major float64 — matching the python fallback's
// dtype so out-of-float32-range values do not silently become inf/0).
// Empty fields and the literal strings na/nan (any case) become NaN.
// Returns the number of rows actually parsed (stops early on a malformed
// row, so the caller can fall back for the remainder or raise).
int64_t mml_parse_csv_f64(const char* buf, int64_t len, char sep,
                          int64_t n_rows, int64_t n_cols, double* out) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t row = 0;
  while (row < n_rows && p < end) {
    // skip blank lines (the python fallback drops them too; a mismatch in
    // parsed-row count makes the caller fall back, keeping both paths
    // consistent on files with interior blanks)
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    for (int64_t c = 0; c < n_cols; ++c) {
      // field start: skip spaces — unless space IS the separator, where
      // merging consecutive seps would diverge from csv.reader's
      // empty-field semantics (such rows abort to the fallback instead)
      if (sep != ' ')
        while (p < end && *p == ' ') ++p;
      const char* fs = p;
      while (p < end && *p != sep && *p != '\n' && *p != '\r') ++p;
      int64_t flen = p - fs;
      double v;
      if (flen == 0 ||
          (flen == 2 && (fs[0] == 'n' || fs[0] == 'N') &&
           (fs[1] == 'a' || fs[1] == 'A')) ||
          (flen == 3 && (fs[0] == 'n' || fs[0] == 'N') &&
           (fs[1] == 'a' || fs[1] == 'A') &&
           (fs[2] == 'n' || fs[2] == 'N'))) {
        v = std::numeric_limits<double>::quiet_NaN();
      } else {
        char* fe = nullptr;
        v = strtod(fs, &fe);
        // strtof may read past sep only if the field is malformed; any
        // unconsumed non-space chars inside the field abort the fast path
        const char* q = fe;
        while (q < fs + flen && *q == ' ') ++q;
        if (fe == fs || q != fs + flen) return row;
      }
      out[row * n_cols + c] = v;
      if (c + 1 < n_cols) {
        if (p >= end || *p != sep) return row;
        ++p;  // consume sep
      }
    }
    // consume end of line (accept \r\n, \n, or EOF)
    if (p < end && *p == '\r') ++p;
    if (p < end) {
      if (*p != '\n') return row;
      ++p;
    }
    ++row;
  }
  return row;
}

}  // extern "C"
