"""API-surface generation from the Params registry.

Reference: the binding autogeneration system (src/test/scala/.../codegen/
CodeGen.scala:15-48 + PySparkWrapper.scala / SparklyRWrapper.scala) reflects
over every `Wrappable` stage to emit PySpark/SparklyR wrappers. The TPU build
is single-language, so codegen shrinks to API-surface generation (SURVEY.md
§7.8): reflect over the same Param registry to emit

- `.pyi` stubs with typed setFoo/getFoo accessors, and
- markdown API docs,

keeping the "single source of truth" property: a param declared once on the
class drives runtime config, serialization, AND the generated surface.
"""

from __future__ import annotations

import importlib
import inspect
import pkgutil
from typing import Dict, List, Optional, Tuple, Type

from ..core.params import Param, Params
from ..core.pipeline import Estimator, Evaluator, Model, Transformer

#: modules scanned for stages (mirrors the reference's jar reflection)
PACKAGES = [
    "mmlspark_tpu.core", "mmlspark_tpu.featurize", "mmlspark_tpu.stages",
    "mmlspark_tpu.models", "mmlspark_tpu.train", "mmlspark_tpu.automl",
    "mmlspark_tpu.nn", "mmlspark_tpu.recommendation", "mmlspark_tpu.explain",
    "mmlspark_tpu.io", "mmlspark_tpu.cyber", "mmlspark_tpu.cognitive",
]


def discover_stages() -> List[Type[Params]]:
    """Import every module under PACKAGES; collect concrete Params classes
    (JarLoadingUtils equivalent)."""
    seen: Dict[str, Type[Params]] = {}
    for pkg_name in PACKAGES:
        pkg = importlib.import_module(pkg_name)
        modules = [pkg]
        if hasattr(pkg, "__path__"):
            for info in pkgutil.walk_packages(pkg.__path__,
                                              pkg_name + "."):
                try:
                    modules.append(importlib.import_module(info.name))
                except ImportError:
                    continue
        for mod in modules:
            for name, obj in vars(mod).items():
                if (inspect.isclass(obj) and issubclass(obj, Params)
                        and obj.__module__.startswith("mmlspark_tpu")
                        and not name.startswith("_")):
                    seen[f"{obj.__module__}.{obj.__name__}"] = obj
    return [seen[k] for k in sorted(seen)]


def _is_abstract(cls: Type[Params]) -> bool:
    if cls in (Params, Transformer, Estimator, Model, Evaluator):
        return True
    name = cls.__name__
    return name.endswith("Base") or name.startswith("Has") or name.endswith(
        "Params") or name.endswith("ParamsBase")


def _py_type(p: Param) -> str:
    if p.converter is int or isinstance(p.default, bool):
        return "bool" if isinstance(p.default, bool) else "int"
    if p.converter is float or isinstance(p.default, float):
        return "float"
    if isinstance(p.default, str):
        return "str"
    if isinstance(p.default, int):
        return "int"
    return "Any"


def generate_stub(cls: Type[Params]) -> str:
    """One class's .pyi body with typed accessors."""
    lines = [f"class {cls.__name__}:"]
    params = cls.params()
    if not params:
        lines.append("    ...")
        return "\n".join(lines)
    for name, p in sorted(params.items()):
        t = _py_type(p)
        cap = name[0].upper() + name[1:]
        lines.append(f"    def set{cap}(self, value: {t}) -> "
                     f"\"{cls.__name__}\": ...")
        lines.append(f"    def get{cap}(self) -> {t}: ...")
    return "\n".join(lines)


def generate_stubs() -> str:
    """Full .pyi content for every discovered concrete stage."""
    parts = ["# auto-generated API stubs — mmlspark_tpu.utils.codegen",
             "from typing import Any", ""]
    for cls in discover_stages():
        if _is_abstract(cls):
            continue
        parts.append(generate_stub(cls))
        parts.append("")
    return "\n".join(parts)


def generate_docs() -> str:
    """Markdown API reference: one section per stage with its param table."""
    out = ["# mmlspark_tpu API reference", "",
           "Auto-generated from the Param registry "
           "(single source of truth).", ""]
    current_pkg = None
    for cls in discover_stages():
        if _is_abstract(cls):
            continue
        pkg = cls.__module__.rsplit(".", 1)[0]
        if pkg != current_pkg:
            out.append(f"## {pkg}")
            out.append("")
            current_pkg = pkg
        kind = ("Estimator" if issubclass(cls, Estimator)
                else "Model" if issubclass(cls, Model)
                else "Transformer" if issubclass(cls, Transformer)
                else "Evaluator" if issubclass(cls, Evaluator)
                else "Component")
        out.append(f"### {cls.__name__} ({kind})")
        doc = inspect.getdoc(cls)
        if doc:
            out.append(doc.split("\n\n")[0])
        params = cls.params()
        if params:
            out.append("")
            out.append("| param | type | default | doc |")
            out.append("|---|---|---|---|")
            for name, p in sorted(params.items()):
                doc_text = (p.doc or "").replace("|", "\\|")
                out.append(f"| {name} | {_py_type(p)} | `{p.default!r}` "
                           f"| {doc_text} |")
        out.append("")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# R bindings (SparklyRWrapper.scala equivalent)
# ---------------------------------------------------------------------------

_R_HEADER = '''# Auto-generated R bindings for mmlspark_tpu — utils/codegen.py.
# Mirrors the reference's SparklyR wrapper generation
# (codegen/SparklyRWrapper.scala): one ml_<stage> function per stage, param
# defaults lifted from the Param registry. The bridge is reticulate instead of
# a JVM gateway: stages are plain Python objects; data.frames cross via
# reticulate's data.frame <-> dict conversion.

.mmlspark_env <- new.env(parent = emptyenv())

.mmlspark_module <- function() {
  if (is.null(.mmlspark_env$mod)) {
    .mmlspark_env$mod <- reticulate::import("mmlspark_tpu")
  }
  .mmlspark_env$mod
}

.mmlspark_new <- function(qualified_name, params) {
  # import the defining module directly: the package __init__ does not
  # re-export every submodule, so attribute-walking from the root would fail
  parts <- strsplit(qualified_name, "\\\\.")[[1]]
  module <- paste(head(parts, -1), collapse = ".")
  cls <- tail(parts, 1)
  stage <- reticulate::import(module)[[cls]]()
  for (name in names(params)) {
    value <- params[[name]]
    if (!is.null(value)) {
      setter <- paste0("set", toupper(substring(name, 1, 1)),
                       substring(name, 2))
      stage[[setter]](value)
    }
  }
  stage
}
'''

_R_FUNC_TEMPLATE = '''
{doc}
ml_{snake} <- function(x{args})
{{
  params <- list({param_list})
  stage <- .mmlspark_new("{qualified}", params)
  df <- .mmlspark_module()$core$dataframe$DataFrame(x)
  {action}
}}'''


def _snake(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i and (
                not name[i - 1].isupper()          # wordStart
                or (i + 1 < len(name) and name[i + 1].islower())):  # GBMNext
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


def _r_default(p: Param) -> str:
    d = p.default
    if isinstance(d, bool):
        return "TRUE" if d else "FALSE"
    if isinstance(d, (int, float)):
        return repr(d)
    if isinstance(d, str):
        return '"' + d.replace("\\", "\\\\").replace('"', '\\"') + '"'
    return "NULL"


def generate_r_wrapper(cls: Type[Params]) -> str:
    """One stage's R function, SparklyRWrapper functionTemplate analogue."""
    snake = _snake(cls.__name__)
    params = sorted(cls.params().items())
    args = "".join(f",\n                {_snake(n)} = {_r_default(p)}"
                   for n, p in params if not p.complex)
    param_list = ", ".join(f"{n} = {_snake(n)}"
                           for n, p in params if not p.complex)
    if issubclass(cls, Estimator):
        action = "stage$fit(df)"
    elif issubclass(cls, (Transformer, Model)):
        action = "stage$transform(df)$to_dict()"
    elif issubclass(cls, Evaluator):
        action = "stage$evaluate(df)"
    else:
        action = "stage"
    doc_lines = [f"#' {cls.__name__}"]
    cls_doc = inspect.getdoc(cls)
    if cls_doc:
        doc_lines += [f"#' {ln}" for ln in
                      cls_doc.split("\n\n")[0].splitlines()]
    doc_lines.append("#' @param x an R data.frame (or named list of columns)")
    for n, p in params:
        if not p.complex:
            doc_lines.append(f"#' @param {_snake(n)} {p.doc or ''}")
    doc_lines.append("#' @export")
    return _R_FUNC_TEMPLATE.format(
        doc="\n".join(doc_lines), snake=snake, args=args,
        param_list=param_list,
        qualified=f"{cls.__module__}.{cls.__name__}", action=action)


def generate_r_wrappers() -> str:
    """Full R source: every concrete stage as an ml_<stage> function."""
    parts = [_R_HEADER]
    for cls in discover_stages():
        if _is_abstract(cls):
            continue
        parts.append(generate_r_wrapper(cls))
    return "\n".join(parts) + "\n"


def write_artifacts(out_dir: str) -> Tuple[str, str, str]:
    """Emit stubs + docs + R bindings (CodeGen.generateArtifacts equivalent)."""
    import os
    os.makedirs(out_dir, exist_ok=True)
    stub_path = os.path.join(out_dir, "mmlspark_tpu.pyi")
    docs_path = os.path.join(out_dir, "API.md")
    r_path = os.path.join(out_dir, "mmlspark_tpu.R")
    with open(stub_path, "w") as f:
        f.write(generate_stubs())
    with open(docs_path, "w") as f:
        f.write(generate_docs())
    with open(r_path, "w") as f:
        f.write(generate_r_wrappers())
    return stub_path, docs_path, r_path


def main(argv=None) -> int:
    """CLI entry (`mmlspark-tpu-codegen OUT_DIR`): emit stubs + docs + R."""
    import argparse
    ap = argparse.ArgumentParser(
        description="Generate mmlspark_tpu API artifacts from the Params "
                    "registry (.pyi stubs, API.md, R bindings)")
    ap.add_argument("out_dir", help="output directory")
    args = ap.parse_args(argv)
    for path in write_artifacts(args.out_dir):
        print(path)
    return 0
