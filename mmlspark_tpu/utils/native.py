"""Native runtime loader — builds and loads the C++ host kernels via ctypes.

Reference analogue: core/env/NativeLoader.java:28-100 — the reference extracts
prebuilt .so files from jar resources and System.load()s them in dependency order.
Here the artifact is built once from the in-tree source (g++ -O3 -shared) into a
per-user cache dir and loaded with ctypes; every caller degrades to a numpy fallback
when the toolchain is unavailable, so the framework never hard-fails on import.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Iterable, Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "native_src", "mmlspark_native.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> str:
    base = os.environ.get("MMLSPARK_TPU_CACHE",
                          os.path.join(tempfile.gettempdir(), "mmlspark_tpu_native"))
    os.makedirs(base, exist_ok=True)
    return base


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"libmmlspark_{digest}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-fPIC", "-shared",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except (subprocess.SubprocessError, OSError, FileNotFoundError):
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """Build-on-demand + load. Returns None when native path is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("MMLSPARK_TPU_NO_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.mml_hash_strings.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_void_p]
        lib.mml_bin_matrix.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.mml_resize_bilinear_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        lib.mml_unroll_chw.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.mml_parse_csv_f64.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
        lib.mml_parse_csv_f64.restype = ctypes.c_int64
        _lib = lib
        return _lib


def hash_strings(strings: Iterable[str], mask: int, seed: int = 0) -> np.ndarray:
    """Batch murmur3 of strings through the C++ kernel."""
    lib = get_lib()
    assert lib is not None
    encoded = [s.encode("utf-8") for s in strings]
    n = len(encoded)
    offsets = np.zeros(n + 1, np.int64)
    for i, b in enumerate(encoded):
        offsets[i + 1] = offsets[i] + len(b)
    blob = b"".join(encoded)
    buf = np.frombuffer(blob, np.uint8) if blob else np.zeros(1, np.uint8)
    out = np.zeros(n, np.int64)
    lib.mml_hash_strings(
        buf.ctypes.data, offsets.ctypes.data, n, seed, mask, out.ctypes.data)
    return out


def bin_matrix(data: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Bin a dense [n,f] float32 matrix by per-feature edges [f,e]."""
    lib = get_lib()
    data = np.ascontiguousarray(data, np.float32)
    edges = np.ascontiguousarray(edges, np.float64)
    n, f = data.shape
    out = np.zeros((n, f), np.int32)
    if lib is not None:
        lib.mml_bin_matrix(data.ctypes.data, n, f, edges.ctypes.data,
                           edges.shape[1], out.ctypes.data)
        return out
    for j in range(f):  # numpy fallback
        out[:, j] = np.searchsorted(edges[j], data[:, j], side="left")
        out[np.isnan(data[:, j]), j] = 0
    return out


def resize_bilinear_u8(img: np.ndarray, dh: int, dw: int) -> np.ndarray:
    """Bilinear-resize an HWC uint8 image."""
    lib = get_lib()
    img = np.ascontiguousarray(img, np.uint8)
    sh, sw, c = img.shape
    if lib is not None:
        dst = np.zeros((dh, dw, c), np.uint8)
        lib.mml_resize_bilinear_u8(img.ctypes.data, sh, sw, c,
                                   dst.ctypes.data, dh, dw)
        return dst
    # numpy fallback: gather with bilinear weights
    ys = np.linspace(0, sh - 1, dh)
    xs = np.linspace(0, sw - 1, dw)
    y0 = np.floor(ys).astype(int); y1 = np.minimum(y0 + 1, sh - 1)
    x0 = np.floor(xs).astype(int); x1 = np.minimum(x0 + 1, sw - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    v00 = img[y0][:, x0]; v01 = img[y0][:, x1]
    v10 = img[y1][:, x0]; v11 = img[y1][:, x1]
    v = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
         v10 * wy * (1 - wx) + v11 * wy * wx)
    return np.clip(np.round(v), 0, 255).astype(np.uint8)


def unroll_chw(img: np.ndarray, scale: np.ndarray, shift: np.ndarray) -> np.ndarray:
    """HWC uint8 -> flat CHW float32 with per-channel normalize."""
    lib = get_lib()
    img = np.ascontiguousarray(img, np.uint8)
    h, w, c = img.shape
    scale = np.ascontiguousarray(scale, np.float32)
    shift = np.ascontiguousarray(shift, np.float32)
    if lib is not None:
        dst = np.zeros(c * h * w, np.float32)
        lib.mml_unroll_chw(img.ctypes.data, h, w, c, scale.ctypes.data,
                           shift.ctypes.data, dst.ctypes.data)
        return dst
    chw = img.astype(np.float32).transpose(2, 0, 1)
    return (chw * scale[:, None, None] + shift[:, None, None]).reshape(-1)


def parse_csv_f64(text: bytes, n_rows: int, n_cols: int,
                  sep: str = ",", offset: int = 0) -> Optional[np.ndarray]:
    """Numeric-CSV fast path: parse a comma-separated text buffer of
    n_rows x n_cols numbers into a row-major float64 matrix via the C++
    kernel (float64 so the dtype matches the python fallback). `offset`
    skips a header prefix without slicing (one less full-buffer copy).
    Returns None when the native library is unavailable OR the buffer is
    not purely numeric (the kernel stops at the first malformed row) —
    callers fall back to the python parser."""
    lib = get_lib()
    if lib is None or n_rows == 0 or n_cols == 0:
        return None
    try:
        sep_b = sep.encode("ascii")
    except UnicodeEncodeError:
        return None          # exotic separator -> python fallback
    if len(sep_b) != 1:
        return None
    # strtod needs a terminated buffer: guarantee a sentinel past the end
    buf = np.frombuffer(text + b"\n\0", np.uint8)
    out = np.empty((n_rows, n_cols), np.float64)
    parsed = lib.mml_parse_csv_f64(buf.ctypes.data + offset,
                                   len(text) - offset,
                                   sep_b, n_rows, n_cols,
                                   out.ctypes.data)
    if parsed != n_rows:
        return None
    return out
