"""Tracing / profiling utilities.

The reference's tracing story is ad-hoc: `StopWatch` wall-time counters
surfaced as a diagnostics DataFrame (core/utils/StopWatch.scala:35,
vw/VowpalWabbitBase.scala:268-303) and the `Timer` wrapper stage
(stages/Timer.scala:18) — both have direct counterparts here (VW perf
stats, stages.Timer). This module adds the TPU-native layer the JVM never
had: XLA device traces via `jax.profiler`, viewable in TensorBoard /
Perfetto, plus a StopWatch with the device-barrier discipline that makes
wall times MEAN something under async dispatch (a `block_until_ready`
before each read — without it, timings measure dispatch, not compute).

    with device_trace("/tmp/trace"):         # XLA trace -> TensorBoard
        model = clf.fit(df)

    sw = StopWatch()
    with sw.measure("fit"):
        model = clf.fit(df)
    print(sw.summary())                       # {'fit': {'total_s': ...}}
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

__all__ = ["device_trace", "annotate", "StopWatch"]


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture an XLA/TPU profiler trace into log_dir for the duration of
    the block (TensorBoard's profile plugin or Perfetto reads it). Device
    work is barriered before stop so in-flight programs land in trace."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        try:
            # flush async dispatch so the trace covers the block's work
            jax.effects_barrier()
        except Exception:
            pass
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a device_trace (jax.profiler.TraceAnnotation);
    harmless when no trace is active."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


class StopWatch:
    """Barrier-aware wall-time accumulator (StopWatch.scala:35 role).

    Each measure() block ends with a `jax.effects_barrier()` so the
    recorded time includes the device work the block dispatched — under
    JAX's async dispatch a bare perf_counter pair measures only Python
    time. Per-name totals/counts mirror the reference's VW TrainingStats
    percentage breakdowns."""

    def __init__(self) -> None:
        self._acc: Dict[str, Dict[str, float]] = {}

    @contextlib.contextmanager
    def measure(self, name: str,
                barrier: bool = True) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if barrier:
                try:
                    import jax
                    jax.effects_barrier()
                except Exception:
                    pass
            dt = time.perf_counter() - t0
            slot = self._acc.setdefault(name,
                                        {"total_s": 0.0, "count": 0.0})
            slot["total_s"] += dt
            slot["count"] += 1

    def summary(self, total_name: Optional[str] = None) -> Dict[str, Any]:
        """Per-name {total_s, count [, pct]} — pct of total_name's time
        when given (the VW diagnostics-DataFrame convention)."""
        out: Dict[str, Any] = {}
        base = (self._acc.get(total_name, {}).get("total_s")
                if total_name else None)
        for name, slot in self._acc.items():
            rec = dict(slot)
            if base:
                rec["pct"] = 100.0 * slot["total_s"] / base
            out[name] = rec
        return out
