"""Tracing / profiling utilities.

The reference's tracing story is ad-hoc: `StopWatch` wall-time counters
surfaced as a diagnostics DataFrame (core/utils/StopWatch.scala:35,
vw/VowpalWabbitBase.scala:268-303) and the `Timer` wrapper stage
(stages/Timer.scala:18) — both have direct counterparts here (VW perf
stats, stages.Timer). This module adds the TPU-native layer the JVM never
had: XLA device traces via `jax.profiler`, viewable in TensorBoard /
Perfetto, plus a StopWatch with the device-barrier discipline that makes
wall times MEAN something under async dispatch (a `block_until_ready`
before each read — without it, timings measure dispatch, not compute).

    with device_trace("/tmp/trace"):         # XLA trace -> TensorBoard
        model = clf.fit(df)

    sw = StopWatch()
    with sw.measure("fit"):
        model = clf.fit(df)
    print(sw.summary())                       # {'fit': {'total_s': ...}}
"""

from __future__ import annotations

import contextlib
import time
import warnings
from typing import Any, Dict, Iterator, List, Optional

__all__ = ["device_trace", "annotate", "StopWatch", "FitTimeline",
           "NULL_TIMELINE"]


def _flush_device_work(jax) -> None:
    """Barrier in-flight device work before a trace stops, version-aware:
    `jax.effects_barrier` where present (0.4+), else block on the live
    arrays still in flight. A barrier that silently fails produces a
    trace that silently MISSES in-flight programs — worse than no trace —
    so every failure path emits a one-line warning instead of swallowing."""
    barrier = getattr(jax, "effects_barrier", None)
    try:
        if barrier is not None:
            barrier()
        elif hasattr(jax, "live_arrays"):
            # older jax without effects_barrier: blocking on the arrays
            # currently alive flushes the async dispatch queue they're on
            jax.block_until_ready(jax.live_arrays())
        else:
            warnings.warn(
                "device_trace: this jax has neither effects_barrier nor "
                "live_arrays — the trace may miss in-flight device work",
                stacklevel=3)
    except Exception as e:  # noqa: BLE001 - trace integrity warning below
        warnings.warn(
            f"device_trace: device flush failed ({type(e).__name__}: {e}) "
            f"— the trace may miss in-flight device work", stacklevel=3)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture an XLA/TPU profiler trace into log_dir for the duration of
    the block (TensorBoard's profile plugin or Perfetto reads it). Device
    work is barriered before stop so in-flight programs land in trace."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        # flush async dispatch so the trace covers the block's work
        _flush_device_work(jax)
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a device_trace (jax.profiler.TraceAnnotation);
    harmless when no trace is active."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


class StopWatch:
    """Barrier-aware wall-time accumulator (StopWatch.scala:35 role).

    Each measure() block ends with a `jax.effects_barrier()` so the
    recorded time includes the device work the block dispatched — under
    JAX's async dispatch a bare perf_counter pair measures only Python
    time. Per-name totals/counts mirror the reference's VW TrainingStats
    percentage breakdowns."""

    def __init__(self) -> None:
        self._acc: Dict[str, Dict[str, float]] = {}

    @contextlib.contextmanager
    def measure(self, name: str,
                barrier: bool = True) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if barrier:
                try:
                    import jax
                    jax.effects_barrier()
                except Exception:
                    pass
            dt = time.perf_counter() - t0
            slot = self._acc.setdefault(name,
                                        {"total_s": 0.0, "count": 0.0})
            slot["total_s"] += dt
            slot["count"] += 1

    def summary(self, total_name: Optional[str] = None) -> Dict[str, Any]:
        """Per-name {total_s, count [, pct]} — pct of total_name's time
        when given (the VW diagnostics-DataFrame convention)."""
        out: Dict[str, Any] = {}
        base = (self._acc.get(total_name, {}).get("total_s")
                if total_name else None)
        for name, slot in self._acc.items():
            rec = dict(slot)
            if base:
                rec["pct"] = 100.0 * slot["total_s"] / base
            out[name] = rec
        return out

    def publish(self, prefix: str = "fit_phase", registry=None) -> None:
        """Land this decomposition in the telemetry registry
        (`<prefix>_seconds{phase=...}` gauges) so a /metrics scrape or a
        bench snapshot carries it — the observability bridge."""
        from ..observability import publish_stopwatch
        publish_stopwatch(self.summary(), prefix=prefix, registry=registry)


class FitTimeline:
    """Barrier-FREE span recorder for the host/device fit pipeline.

    Where StopWatch adds a device barrier per block (correct for phase
    decompositions, fatal for measuring overlap — the barrier serializes
    exactly the concurrency under measurement), FitTimeline records plain
    host-clock intervals and never touches the device. Spans carry a kind:

    - ``host``   — real host busy time (binning a block, bookkeeping,
      dispatching a transfer or a chunk);
    - ``wait``   — host blocked on the device (the designated commit
      barrier, a chunk-result fetch): EXPOSED device time;
    - ``device`` — device-side work whose duration is known only by
      estimate/calibration (``add_span(..., estimated dur)``): transfer
      backlog that ran concurrently with host spans.

    ``overlap_ratio`` is the standard two-stream pipelining metric: with
    host total H, device total D and construction wall W (real spans
    only), a fully serial stage costs H + D and a perfectly overlapped
    one max(H, D), so

        overlap_ratio = clip((H + D - W) / min(H, D), 0, 1)

    1.0 = the smaller stream is entirely hidden under the larger one.
    ``summary()`` additionally proves ahead-dispatch for chunk-loop
    timelines structurally: every ``dispatch[k+1]`` span must begin
    before ``fetch_wait[k]`` does (the next device program is in flight
    before the host blocks on the previous one's results).
    """

    def __init__(self) -> None:
        self._t0 = time.perf_counter()
        self.spans: List[Dict[str, Any]] = []
        self.meta: Dict[str, Any] = {}

    @contextlib.contextmanager
    def span(self, name: str, kind: str = "host") -> Iterator[None]:
        t0 = time.perf_counter() - self._t0
        try:
            yield
        finally:
            self.spans.append({"name": name, "kind": kind, "t0_s": t0,
                               "t1_s": time.perf_counter() - self._t0})

    def add_span(self, name: str, kind: str, dur_s: float) -> None:
        """Record an ESTIMATED span (e.g. calibrated transfer backlog):
        excluded from the wall, included in the per-kind totals. The true
        duration is stored explicitly (`dur_s`) so an estimate longer
        than the elapsed timeline is never truncated by the display
        clamp on t0."""
        t1 = time.perf_counter() - self._t0
        self.spans.append({"name": name, "kind": kind,
                           "t0_s": max(0.0, t1 - dur_s), "t1_s": t1,
                           "dur_s": dur_s, "estimated": True})

    @property
    def wall_s(self) -> float:
        real = [s for s in self.spans if not s.get("estimated")]
        if not real:
            return 0.0
        return (max(s["t1_s"] for s in real)
                - min(s["t0_s"] for s in real))

    def totals(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for s in self.spans:
            dur = s.get("dur_s", s["t1_s"] - s["t0_s"])
            out[s["kind"]] = out.get(s["kind"], 0.0) + dur
        return out

    def overlap_ratio(self) -> Optional[float]:
        t = self.totals()
        host, dev = t.get("host", 0.0), t.get("device", 0.0)
        lo = min(host, dev)
        if lo <= 0.0:
            return None
        return round(max(0.0, min(1.0, (host + dev - self.wall_s) / lo)), 4)

    def _ahead_dispatch(self) -> Optional[bool]:
        """True iff every dispatch[k+1] begins before fetch_wait[k] —
        the structural proof that the chunk loop runs ahead of its own
        host bookkeeping. None when the timeline has < 2 chunks."""
        disp: Dict[str, float] = {}
        fw: Dict[str, float] = {}
        order: List[str] = []
        for s in self.spans:
            n = s["name"]
            if n.startswith("dispatch[") and n.endswith("]"):
                disp[n[9:-1]] = s["t0_s"]
                order.append(n[9:-1])
            elif n.startswith("fetch_wait[") and n.endswith("]"):
                fw[n[11:-1]] = s["t0_s"]
        if len(order) < 2 or not fw:
            return None
        ok = True
        for prev, nxt in zip(order, order[1:]):
            if prev in fw:
                ok = ok and disp[nxt] < fw[prev]
        return ok

    def summary(self) -> Dict[str, Any]:
        t = self.totals()
        out: Dict[str, Any] = {
            "wall_s": round(self.wall_s, 4),
            "host_busy_s": round(t.get("host", 0.0), 4),
            "device_busy_s": round(t.get("device", 0.0), 4),
            "wait_s": round(t.get("wait", 0.0), 4),
            "spans": [{**s, "t0_s": round(s["t0_s"], 4),
                       "t1_s": round(s["t1_s"], 4),
                       **({"dur_s": round(s["dur_s"], 4)}
                          if "dur_s" in s else {})} for s in self.spans],
        }
        orat = self.overlap_ratio()
        if orat is not None:
            out["overlap_ratio"] = orat
        ahead = self._ahead_dispatch()
        if ahead is not None:
            out["ahead_dispatch"] = ahead
        out.update({k: v for k, v in self.meta.items()})
        return out

    def publish(self, prefix: str = "fit_pipeline", registry=None) -> None:
        """Land overlap_ratio / commit_wait / busy totals in the telemetry
        registry — the observability bridge for pipelined fits."""
        from ..observability import publish_fit_timeline
        publish_fit_timeline(self.summary(), prefix=prefix,
                             registry=registry)


def fit_pipeline_overlap_record(fit_timings: Dict[str, Any],
                                seq_phases: Optional[Dict[str, float]] = None
                                ) -> Optional[Dict[str, Any]]:
    """The ONE assembly of the pipelined-fit overlap record (consumed by
    bench.py extras and scripts/measure_fit_pipeline.py rows — a single
    definition so the like-named metrics in BENCH json and
    PERF_fit_pipeline.log can never be computed differently).

    fit_timings: a booster's `fit_timings` from a `fitPipeline='on'` +
    `collectFitTimings=True` fit. seq_phases: optionally, the phase dict
    of a SEQUENTIAL (`fitPipeline='off'`) decomposition of the same
    problem ({'binning': s, 'device_transfer': s, ...}) — when present,
    the cross-run ratio 1 - pipelined_construction / (binning + transfer)
    is included. Returns None when fit_timings has no timeline."""
    tl = (fit_timings or {}).get("timeline") or {}
    cons = tl.get("construction")
    if cons is None:
        return None
    rec: Dict[str, Any] = {
        "construction_s": round(cons["wall_s"], 3),
        "host_busy_s": cons["host_busy_s"],
        "commit_wait_s": cons["wait_s"],
        "transfer_est_s": cons["device_busy_s"],
        "overlap_ratio": cons.get("overlap_ratio"),
    }
    if seq_phases and "binning" in seq_phases \
            and "device_transfer" in seq_phases:
        serial = seq_phases["binning"] + seq_phases["device_transfer"]
        if serial > 0:
            rec["cross_run_overlap_ratio"] = round(
                1.0 - cons["wall_s"] / serial, 4)
    if "chunks" in tl:
        rec["chunks_ahead_dispatch"] = tl["chunks"].get("ahead_dispatch")
    return rec


class _NullTimeline:
    """No-op FitTimeline stand-in so pipeline code needs no `if timeline`
    branching on the hot path."""

    def __init__(self) -> None:
        self.meta: Dict[str, Any] = {}

    def span(self, name: str, kind: str = "host"):
        return contextlib.nullcontext()

    def add_span(self, name: str, kind: str, dur_s: float) -> None:
        pass


NULL_TIMELINE = _NullTimeline()
