"""Cognitive-services transformer layer (reference: cognitive/, 21 files,
3964 LoC — pure HTTP clients over the io/http stack)."""

from .base import CognitiveServicesBase, ServiceParam
from .services import (NER, OCR, AnalyzeImage, AzureSearchWriter,
                       BingImageSearch, DescribeImage, DetectAnomalies,
                       DetectFace, DetectLastAnomaly, FindSimilarFace,
                       GenerateThumbnails, GroupFaces, IdentifyFaces,
                       KeyPhraseExtractor, LanguageDetector, RecognizeText,
                       SpeechToText, SpeechToTextStreaming, TagImage,
                       TextSentiment, VerifyFaces)

__all__ = [
    "CognitiveServicesBase", "ServiceParam",
    "TextSentiment", "KeyPhraseExtractor", "NER", "LanguageDetector",
    "OCR", "AnalyzeImage", "DescribeImage", "TagImage", "GenerateThumbnails",
    "RecognizeText",
    "DetectFace", "VerifyFaces", "FindSimilarFace", "GroupFaces",
    "IdentifyFaces",
    "DetectLastAnomaly", "DetectAnomalies",
    "BingImageSearch", "AzureSearchWriter", "SpeechToText",
    "SpeechToTextStreaming",
]
