"""Cognitive-service transformer base — external HTTP AI services as stages.

Reference: cognitive/CognitiveServiceBase.scala:258-330 — every service
transformer is internally `Lambda(prep) -> HTTPTransformer ->
JSONOutputParser -> DropColumns`; `ServiceParam[T]` (:29-152) holds a
scalar-or-column ("left/right") value so any request field can come from a
constant or a per-row column. Auth via subscription-key header; url =
endpoint template + location.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Transformer
from ..io.http import (AsyncClient, HTTPRequestData, HTTPResponseData,
                       JSONOutputParser)


class ServiceParam:
    """Scalar-or-column value (CognitiveServiceBase.scala:29-152).

    `ServiceParam.value(x)` = constant for all rows; `ServiceParam.col(name)`
    = read per-row from that column."""

    def __init__(self, value: Any = None, col: Optional[str] = None):
        self._value = value
        self._col = col

    @staticmethod
    def value(v: Any) -> "ServiceParam":
        return ServiceParam(value=v)

    @staticmethod
    def col(name: str) -> "ServiceParam":
        return ServiceParam(col=name)

    def resolve(self, df: DataFrame, i: int) -> Any:
        if self._col is not None:
            return df[self._col][i]
        return self._value

    def __repr__(self):
        return (f"ServiceParam(col={self._col!r})" if self._col
                else f"ServiceParam({self._value!r})")


def _as_service_param(v: Any) -> ServiceParam:
    return v if isinstance(v, ServiceParam) else ServiceParam(value=v)


class CognitiveServicesBase(Transformer, _p.HasOutputCol):
    """Shared request/response plumbing. Subclasses define `urlPath` and
    override `prepare_entity(df, i) -> (dict|bytes|None)` plus optionally
    `url_params(df, i)` / `extract(parsed)`."""

    subscriptionKey = _p.Param("subscriptionKey",
                               "service key (ServiceParam)", None,
                               complex=True, converter=_as_service_param)
    url = _p.Param("url", "full service url (overrides location template)",
                   None)
    location = _p.Param("location", "service region for the url template",
                        "eastus")
    errorCol = _p.Param("errorCol", "error info column", "error")
    concurrency = _p.Param("concurrency", "parallel requests", 4, int)
    timeout = _p.Param("timeout", "per-request timeout s", 60.0, float)
    retryPolicy = _p.Param("retryPolicy",
                           "resilience.RetryPolicy for request retries "
                           "(None = the shared default backoff array)",
                           None, complex=True)

    service_name: str = ""   # e.g. "text/analytics/v3.0/sentiment"
    method: str = "POST"

    def __init__(self, **kw):
        kw.setdefault("outputCol", type(self).__name__.lower())
        super().__init__(**kw)

    # -------------------------------------------------------- overridables
    def base_url(self) -> str:
        if self.get("url"):
            return self.get("url")
        return (f"https://{self.get('location')}.api.cognitive.microsoft.com/"
                f"{self.service_name}")

    def url_params(self, df: DataFrame, i: int) -> Dict[str, str]:
        return {}

    def prepare_entity(self, df: DataFrame, i: int):
        raise NotImplementedError

    def extract(self, parsed: Any) -> Any:
        """Pull the useful payload out of the parsed JSON response."""
        return parsed

    def headers(self, df: DataFrame, i: int) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        key_param = self.get("subscriptionKey")
        if key_param is not None:
            key = key_param.resolve(df, i)
            if key:
                h["Ocp-Apim-Subscription-Key"] = str(key)
        return h

    # ------------------------------------------------------------ pipeline
    def transform(self, df: DataFrame) -> DataFrame:
        reqs: List[Optional[HTTPRequestData]] = []
        for i in range(len(df)):
            entity = self.prepare_entity(df, i)
            if entity is None:
                reqs.append(None)
                continue
            url = self.base_url()
            params = self.url_params(df, i)
            if params:
                from urllib.parse import urlencode
                url = url + "?" + urlencode(params)
            body = (entity if isinstance(entity, bytes)
                    else json.dumps(entity).encode("utf-8"))
            reqs.append(HTTPRequestData(url=url, method=self.method,
                                        headers=self.headers(df, i),
                                        entity=body))
        client = AsyncClient(self.get("concurrency"), self.get("timeout"),
                             policy=self.get("retryPolicy"))
        resps = client.send_all(reqs)
        out = np.empty(len(df), dtype=object)
        errors = np.empty(len(df), dtype=object)
        for i, r in enumerate(resps):
            errors[i] = None
            if r is None:
                out[i] = None
            elif not (200 <= r.statusCode < 300):
                out[i] = None
                errors[i] = f"{r.statusCode} {r.reasonPhrase}"
            else:
                try:
                    out[i] = self.extract(
                        json.loads(r.entity.decode("utf-8"))
                        if r.entity else None)
                except (ValueError, UnicodeDecodeError) as e:
                    out[i] = None
                    errors[i] = f"parse error: {e}"
        return (df.with_column(self.get("outputCol"), out)
                  .with_column(self.get("errorCol"), errors))
