"""Concrete cognitive-service transformers.

Reference: the ~20 transformers of cognitive/ (SURVEY.md §2.3 cognitive —
3964 LoC): TextAnalytics (TextAnalytics.scala: sentiment, key phrases, NER,
language), ComputerVision (ComputerVision.scala: OCR, analyze, describe, tags,
thumbnails), Face (Face.scala), AnomalyDetector (AnamolyDetection.scala),
BingImageSearch (BingImageSearch.scala), AzureSearch sink (AzureSearch.scala +
AzureSearchAPI.scala), SpeechToText (SpeechToText.scala REST path).

Each class = url path + per-row payload prep + response extraction over
CognitiveServicesBase; all payload shapes follow the public API wire formats.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from .base import CognitiveServicesBase, ServiceParam, _as_service_param


# ------------------------------------------------------------ Text Analytics

class _TextAnalyticsBase(CognitiveServicesBase):
    """documents: [{id, text, language}] envelope (TextAnalytics.scala)."""
    textCol = _p.Param("textCol", "input text column", "text")
    languageCol = _p.Param("languageCol", "per-row language column (optional)",
                           None)
    language = _p.Param("language", "default language", "en")

    def prepare_entity(self, df: DataFrame, i: int):
        text = df[self.get("textCol")][i]
        if text is None:
            return None
        lang_col = self.get("languageCol")
        lang = (df[lang_col][i] if lang_col and lang_col in df
                else self.get("language"))
        return {"documents": [{"id": "0", "text": str(text),
                               "language": lang}]}

    def extract(self, parsed):
        docs = (parsed or {}).get("documents") or []
        return docs[0] if docs else None


class TextSentiment(_TextAnalyticsBase):
    service_name = "text/analytics/v3.0/sentiment"


class KeyPhraseExtractor(_TextAnalyticsBase):
    service_name = "text/analytics/v3.0/keyPhrases"

    def extract(self, parsed):
        doc = super().extract(parsed)
        return doc.get("keyPhrases") if doc else None


class NER(_TextAnalyticsBase):
    service_name = "text/analytics/v3.0/entities/recognition/general"

    def extract(self, parsed):
        doc = super().extract(parsed)
        return doc.get("entities") if doc else None


class LanguageDetector(_TextAnalyticsBase):
    service_name = "text/analytics/v3.0/languages"

    def prepare_entity(self, df: DataFrame, i: int):
        text = df[self.get("textCol")][i]
        if text is None:
            return None
        return {"documents": [{"id": "0", "text": str(text)}]}

    def extract(self, parsed):
        doc = (parsed or {}).get("documents") or []
        if not doc:
            return None
        langs = doc[0].get("detectedLanguage") or doc[0].get(
            "detectedLanguages")
        return langs


# ------------------------------------------------------------ Computer Vision

class _VisionBase(CognitiveServicesBase):
    """Accepts an image url column OR raw image bytes column
    (ComputerVision.scala `HasImageInput`)."""
    imageUrlCol = _p.Param("imageUrlCol", "image url column", None)
    imageBytesCol = _p.Param("imageBytesCol", "raw image bytes column", None)

    def headers(self, df, i):
        h = super().headers(df, i)
        if self.get("imageBytesCol"):
            h["Content-Type"] = "application/octet-stream"
        return h

    def prepare_entity(self, df: DataFrame, i: int):
        if self.get("imageUrlCol"):
            url = df[self.get("imageUrlCol")][i]
            return {"url": str(url)} if url else None
        if self.get("imageBytesCol"):
            data = df[self.get("imageBytesCol")][i]
            return bytes(data) if data is not None else None
        raise ValueError("set imageUrlCol or imageBytesCol")


class OCR(_VisionBase):
    service_name = "vision/v2.0/ocr"
    detectOrientation = _p.Param("detectOrientation", "detect rotation", True,
                                 bool)

    def url_params(self, df, i):
        return {"detectOrientation": str(self.get("detectOrientation")
                                         ).lower()}


class AnalyzeImage(_VisionBase):
    service_name = "vision/v2.0/analyze"
    visualFeatures = _p.Param("visualFeatures", "feature list",
                              None)

    def url_params(self, df, i):
        feats = self.get("visualFeatures") or ["Categories"]
        return {"visualFeatures": ",".join(feats)}


class DescribeImage(_VisionBase):
    service_name = "vision/v2.0/describe"
    maxCandidates = _p.Param("maxCandidates", "caption candidates", 1, int)

    def url_params(self, df, i):
        return {"maxCandidates": str(self.get("maxCandidates"))}


class TagImage(_VisionBase):
    service_name = "vision/v2.0/tag"

    def extract(self, parsed):
        return (parsed or {}).get("tags")


class GenerateThumbnails(_VisionBase):
    service_name = "vision/v2.0/generateThumbnail"
    width = _p.Param("width", "thumbnail width", 64, int)
    height = _p.Param("height", "thumbnail height", 64, int)
    smartCropping = _p.Param("smartCropping", "smart crop", True, bool)

    def url_params(self, df, i):
        return {"width": str(self.get("width")),
                "height": str(self.get("height")),
                "smartCropping": str(self.get("smartCropping")).lower()}


class RecognizeText(_VisionBase):
    service_name = "vision/v2.0/recognizeText"
    mode = _p.Param("mode", "Handwritten | Printed", "Printed")

    def url_params(self, df, i):
        return {"mode": self.get("mode")}


# ------------------------------------------------------------------- Face

class DetectFace(_VisionBase):
    service_name = "face/v1.0/detect"
    returnFaceAttributes = _p.Param("returnFaceAttributes",
                                    "attribute list", None)

    def url_params(self, df, i):
        attrs = self.get("returnFaceAttributes")
        return ({"returnFaceAttributes": ",".join(attrs)} if attrs else {})


class VerifyFaces(CognitiveServicesBase):
    service_name = "face/v1.0/verify"
    faceId1Col = _p.Param("faceId1Col", "first face id column", "faceId1")
    faceId2Col = _p.Param("faceId2Col", "second face id column", "faceId2")

    def prepare_entity(self, df, i):
        return {"faceId1": str(df[self.get("faceId1Col")][i]),
                "faceId2": str(df[self.get("faceId2Col")][i])}


class FindSimilarFace(CognitiveServicesBase):
    service_name = "face/v1.0/findsimilars"
    faceIdCol = _p.Param("faceIdCol", "probe face id column", "faceId")
    faceIdsCol = _p.Param("faceIdsCol", "candidate face ids column", "faceIds")

    def prepare_entity(self, df, i):
        return {"faceId": str(df[self.get("faceIdCol")][i]),
                "faceIds": [str(x) for x in df[self.get("faceIdsCol")][i]]}


class GroupFaces(CognitiveServicesBase):
    service_name = "face/v1.0/group"
    faceIdsCol = _p.Param("faceIdsCol", "face ids column", "faceIds")

    def prepare_entity(self, df, i):
        return {"faceIds": [str(x) for x in df[self.get("faceIdsCol")][i]]}


class IdentifyFaces(CognitiveServicesBase):
    service_name = "face/v1.0/identify"
    faceIdsCol = _p.Param("faceIdsCol", "face ids column", "faceIds")
    personGroupId = _p.Param("personGroupId", "person group", None)

    def prepare_entity(self, df, i):
        return {"faceIds": [str(x) for x in df[self.get("faceIdsCol")][i]],
                "personGroupId": self.get("personGroupId")}


# --------------------------------------------------------- Anomaly Detector

class _AnomalyBase(CognitiveServicesBase):
    """series payload: [{timestamp, value}...] (AnamolyDetection.scala)."""
    seriesCol = _p.Param("seriesCol",
                         "column of [(timestamp, value)] series", "series")
    granularity = _p.Param("granularity", "hourly | daily | ...", "daily")
    sensitivity = _p.Param("sensitivity", "0-99", None)

    def prepare_entity(self, df, i):
        series = df[self.get("seriesCol")][i]
        if series is None:
            return None
        body = {"granularity": self.get("granularity"),
                "series": [{"timestamp": str(t), "value": float(v)}
                           for t, v in series]}
        if self.get("sensitivity") is not None:
            body["sensitivity"] = self.get("sensitivity")
        return body


class DetectLastAnomaly(_AnomalyBase):
    service_name = "anomalydetector/v1.0/timeseries/last/detect"


class DetectAnomalies(_AnomalyBase):
    service_name = "anomalydetector/v1.0/timeseries/entire/detect"


# ------------------------------------------------------------------ Search

class BingImageSearch(CognitiveServicesBase):
    service_name = "bing/v7.0/images/search"
    method = "GET"
    queryCol = _p.Param("queryCol", "search query column", "query")
    count = _p.Param("count", "results per query", 10, int)

    def base_url(self) -> str:
        return self.get("url") or "https://api.bing.microsoft.com/v7.0/images/search"

    def prepare_entity(self, df, i):
        return b""  # GET

    def url_params(self, df, i):
        return {"q": str(df[self.get("queryCol")][i]),
                "count": str(self.get("count"))}

    def extract(self, parsed):
        return (parsed or {}).get("value")


class AzureSearchWriter:
    """Index documents into Azure Cognitive Search (AzureSearch.scala +
    AzureSearchAPI.scala index upload)."""

    @staticmethod
    def write_to_azure_search(df: DataFrame, url: str, api_key: str,
                              action: str = "mergeOrUpload",
                              batch_size: int = 100) -> int:
        from ..io.http import HTTPRequestData, send_with_retries
        rows = df.collect()
        n = 0
        for start in range(0, len(rows), batch_size):
            chunk = rows[start:start + batch_size]
            docs = []
            for r in chunk:
                d = {"@search.action": action}
                for k, v in r.items():
                    if isinstance(v, np.ndarray):
                        v = v.tolist()
                    elif isinstance(v, (np.integer,)):
                        v = int(v)
                    elif isinstance(v, (np.floating,)):
                        v = float(v)
                    d[k] = v
                docs.append(d)
            resp = send_with_retries(HTTPRequestData(
                url=url, method="POST",
                headers={"Content-Type": "application/json",
                         "api-key": api_key},
                entity=json.dumps({"value": docs}).encode("utf-8")))
            if not (200 <= resp.statusCode < 300):
                raise RuntimeError(f"azure search write failed: "
                                   f"{resp.statusCode} {resp.reasonPhrase}")
            n += 1
        return n

    writeToAzureSearch = write_to_azure_search


# ------------------------------------------------------------------ Speech

class SpeechToText(CognitiveServicesBase):
    """REST short-audio transcription (SpeechToText.scala; the native
    streaming SDK path — SpeechToTextSDK.scala — is a remote-service client
    out of the TPU build's scope per SURVEY.md §2.1)."""
    audioBytesCol = _p.Param("audioBytesCol", "audio bytes column", "audio")
    languageParam = _p.Param("languageParam", "BCP-47 language", "en-US")
    format = _p.Param("format", "simple | detailed", "simple")

    def base_url(self) -> str:
        return (self.get("url")
                or f"https://{self.get('location')}.stt.speech.microsoft.com/"
                   f"speech/recognition/conversation/cognitiveservices/v1")

    def headers(self, df, i):
        h = super().headers(df, i)
        h["Content-Type"] = "audio/wav"
        return h

    def url_params(self, df, i):
        return {"language": self.get("languageParam"),
                "format": self.get("format")}

    def prepare_entity(self, df, i):
        data = df[self.get("audioBytesCol")][i]
        return bytes(data) if data is not None else None


class SpeechToTextStreaming(SpeechToText):
    """Streaming transcription: chunked-transfer REST upload with interim
    hypotheses — the client-level analogue of the native-SDK streaming path
    (cognitive/SpeechToTextSDK.scala:66, the one §2.1 component the REST
    `SpeechToText` alone did not cover).

    Protocol: the audio column's bytes are uploaded with
    `Transfer-Encoding: chunked` in `chunkSize`-byte chunks (the SDK streams
    ~100ms audio frames the same way), and the service answers with
    newline-delimited JSON events, read incrementally off the socket:
      {"type": "speech.hypothesis", "Text": ...}   interim partial results
      {"type": "speech.phrase", "DisplayText": ..., "Offset": ...,
       "Duration": ...}                            finalized segments
    (the event names mirror the Speech SDK's `Recognizing`/`Recognized`
    callbacks surfaced by SpeechToTextSDK's flushing serializer).

    Output: `outputCol` holds the list of finalized phrase dicts per row;
    `hypothesesCol` the interim texts. `on_event(row_idx, event)` fires as
    each event arrives — the streaming consumption surface (the SDK's
    subscriber callbacks); it sees hypotheses before transform returns.
    """

    chunkSize = _p.Param("chunkSize", "upload chunk bytes", 32768, int)
    hypothesesCol = _p.Param("hypothesesCol",
                             "interim hypothesis texts column", "hypotheses")

    def __init__(self, on_event=None, **kw):
        super().__init__(**kw)
        self._on_event = on_event

    def _stream_row(self, df: DataFrame, i: int):
        """Upload one row's audio chunked and consume its event stream.
        Returns (finals, hypotheses, error)."""
        import http.client
        from urllib.parse import urlencode, urlsplit

        finals: list = []
        hyps: list = []
        chunk_size = int(self.get("chunkSize"))
        audio = self.prepare_entity(df, i)
        if audio is None:
            return finals, hyps, None
        parts = urlsplit(self.base_url())
        qs = urlencode(self.url_params(df, i))
        path = (parts.path or "/") + ("?" + qs if qs else "")
        conn_cls = (http.client.HTTPSConnection if parts.scheme == "https"
                    else http.client.HTTPConnection)
        conn = conn_cls(parts.netloc, timeout=self.get("timeout"))
        try:
            conn.putrequest("POST", path)
            for k, v in self.headers(df, i).items():
                conn.putheader(k, v)
            conn.putheader("Transfer-Encoding", "chunked")
            conn.endheaders()
            for start in range(0, len(audio), chunk_size):
                chunk = audio[start:start + chunk_size]
                conn.send(b"%x\r\n%s\r\n" % (len(chunk), chunk))
            conn.send(b"0\r\n\r\n")
            resp = conn.getresponse()
            if resp.status != 200:
                return finals, hyps, (
                    f"{resp.status} "
                    f"{resp.read(200).decode('utf-8', 'replace')}")
            # read events incrementally as the service emits them
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                if self._on_event is not None:
                    self._on_event(i, event)
                if event.get("type") == "speech.hypothesis":
                    hyps.append(event.get("Text", ""))
                elif event.get("type") == "speech.phrase":
                    finals.append(
                        {k: event[k] for k in
                         ("DisplayText", "Offset", "Duration")
                         if k in event})
        except (OSError, http.client.HTTPException) as e:
            # per-row failures land in errorCol, never abort the batch
            # (the CognitiveServicesBase contract)
            return finals, hyps, str(e)
        finally:
            conn.close()
        return finals, hyps, None

    def transform(self, df: DataFrame) -> DataFrame:
        from concurrent.futures import ThreadPoolExecutor

        n = len(df)
        finals = np.empty(n, dtype=object)
        hyps = np.empty(n, dtype=object)
        errors = np.empty(n, dtype=object)
        workers = max(1, int(self.get("concurrency")))
        if n and workers > 1:
            with ThreadPoolExecutor(max_workers=workers) as pool:
                results = list(pool.map(lambda i: self._stream_row(df, i),
                                        range(n)))
        else:
            results = [self._stream_row(df, i) for i in range(n)]
        for i, (fi, hi, ei) in enumerate(results):
            finals[i], hyps[i], errors[i] = fi, hi, ei
        out = df.with_column(self.get("outputCol"), finals)
        out = out.with_column(self.get("hypothesesCol"), hyps)
        return out.with_column(self.get("errorCol"), errors)
